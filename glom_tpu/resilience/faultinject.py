"""Seeded, deterministic fault injection.

Real TPU fleets die on torn checkpoint writes, poisoned batches, and
flaky reload I/O — failure modes that unit tests rarely reproduce because
they live in the seams between subsystems.  This module makes them
reproducible: a :class:`FaultPlan`, parsed from a compact spec string,
arms named *injection sites* that production code threads through its
seams.  The same spec and seed always produce the same faults, so a chaos
scenario (``tools/chaos.py``) is a deterministic test, not a dice roll.

Spec grammar (entries separated by ``;``)::

    site:kind[@[step]N][*COUNT]

      ckpt_write:torn@step120       torn artifact write at save step 120
      data:nan_batch@37             NaN batch on the 37th batch drawn
      reload:io_error*3             I/O error on the first 3 reload polls
      data:delay@5*2                delayed batches 5 and 6

``@N`` pins the fault to occurrence ``N`` of the site (the step number the
site reports, or the site's own 1-based call counter when it reports
none); ``*COUNT`` fires it ``COUNT`` consecutive times (default 1).  A
fault with neither fires on the site's first occurrence.

Sites (the names production code passes to :func:`fire`):

  ==========  ============================  =================================
  site        kinds                         threaded into
  ==========  ============================  =================================
  ckpt_write  torn, bitflip                 ``checkpoint.save`` (artifact
                                            corrupted after the atomic write
                                            — the "crashed mid-write /
                                            silent media corruption" class)
  data        nan_batch, drop_batch,        ``training/data.py`` batch
              delay, crash                  iterators (poisoned / lost /
                                            stalled input, pipeline crash)
  reload      io_error, corrupt_manifest    ``serving/engine.py`` hot-reload
                                            watcher polls
  candidate   delay, error                  ``serving/deploy.py`` candidate
                                            executes (a regressing shadow/
                                            canary deploy candidate)
  host_preempt       kill                   ``resilience/elastic.py`` per-step
                                            tick (one fault domain dies)
  coordinator_loss   lost                   elastic tick (coordinator stops
                                            heartbeating; successor elected)
  heartbeat_delay    delay                  elastic tick (a host misses beats
                                            WITHOUT dying — must not eject)
  shrink_restart     shrink, grow           elastic re-plan (the restart
                                            comes back with fewer/more hosts)
  ==========  ============================  =================================

Arming is process-global (:func:`arm` / :func:`disarm` / the
:func:`injected` context manager): the sites live deep inside library code
where no plan object could be threaded without polluting every signature.
Disarmed cost is one module-global ``is None`` check per site call —
nothing on the hot path pays for the capability.

Stdlib only; no jax import.
"""

from __future__ import annotations

import contextlib
import random
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

KINDS = {
    "ckpt_write": ("torn", "bitflip"),
    "data": ("nan_batch", "drop_batch", "delay", "crash"),
    "reload": ("io_error", "corrupt_manifest"),
    # deploy-candidate regression (glom_tpu.serving.deploy): fired once
    # per candidate execute (shadow mirror or live canary batch) —
    # "delay" makes the candidate measurably slow (client-visible latency
    # on canary traffic, never an error), "error" fails the execute
    "candidate": ("delay", "error"),
    # deploy-candidate WEIGHT corruption (glom_tpu.serving.deploy):
    # fired once at candidate load, AFTER integrity verification — the
    # candidate loads clean and serves without errors but computes
    # garbage; only the shadow lane's quality comparison can catch it
    "candidate_load": ("bitflip",),
    # elastic multi-host sites (glom_tpu.resilience.elastic): fired from
    # ElasticContext.tick (the per-global-step seam) and the supervisor's
    # re-plan, so every recovery path is deterministic on CPU
    "host_preempt": ("kill",),
    "coordinator_loss": ("lost",),
    "heartbeat_delay": ("delay",),
    "shrink_restart": ("shrink", "grow"),
}


class FaultError(OSError):
    """The exception injected faults raise (``reload:io_error``,
    ``data:crash``).  An OSError subclass so code hardened against real
    transient I/O errors handles the injected kind identically."""


@dataclass
class Fault:
    """One armed fault: fire at occurrences ``[at, at + count)`` of
    ``site`` (``at=None`` => the site's first occurrence)."""

    site: str
    kind: str
    at: Optional[int] = None
    count: int = 1
    fired: int = field(default=0, compare=False)

    def matches(self, occurrence: int) -> bool:
        if self.fired >= self.count:
            return False
        start = self.at if self.at is not None else 1
        return start <= occurrence < start + self.count

    def spec(self) -> str:
        s = f"{self.site}:{self.kind}"
        if self.at is not None:
            s += f"@{self.at}"
        if self.count != 1:
            s += f"*{self.count}"
        return s


_ENTRY = re.compile(
    r"^(?P<site>[a-z_]+):(?P<kind>[a-z_]+)"
    r"(?:@(?:step)?(?P<at>\d+))?"
    r"(?:\*(?P<count>\d+))?$"
)


class FaultPlan:
    """A parsed, seeded set of faults plus per-site occurrence counters.

    ``fire(site, step=...)`` consumes one occurrence of ``site`` and
    returns the kind of the first eligible fault (marking one firing) or
    None.  Counters and firing state make replay deterministic: parsing
    the same spec with the same seed and driving the sites identically
    yields the identical fault sequence.  Thread-safe — the serving
    watcher and a training loop may share one armed plan.
    """

    def __init__(self, faults: List[Fault], *, seed: int = 0, spec: str = ""):
        self.faults = faults
        self.seed = seed
        self.spec = spec
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, *, seed: int = 0) -> "FaultPlan":
        faults = []
        for raw in spec.split(";"):
            entry = raw.strip()
            if not entry:
                continue
            m = _ENTRY.match(entry)
            if m is None:
                raise ValueError(
                    f"bad fault spec entry {entry!r}; expected "
                    f"'site:kind[@[step]N][*COUNT]'"
                )
            site, kind = m.group("site"), m.group("kind")
            if site not in KINDS:
                raise ValueError(
                    f"unknown fault site {site!r}; one of {sorted(KINDS)}"
                )
            if kind not in KINDS[site]:
                raise ValueError(
                    f"unknown kind {kind!r} for site {site!r}; one of "
                    f"{KINDS[site]}"
                )
            at = m.group("at")
            count = m.group("count")
            faults.append(Fault(
                site, kind,
                at=int(at) if at is not None else None,
                count=int(count) if count is not None else 1,
            ))
        return cls(faults, seed=seed, spec=spec)

    def fire(self, site: str, *, step: Optional[int] = None) -> Optional[str]:
        """One occurrence of ``site``: ``step`` is the site's own notion of
        position (save step, batch index); when None the plan counts calls
        per site, 1-based.  Returns the fired fault's kind or None."""
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            occurrence = step if step is not None else self._calls[site]
            for f in self.faults:
                if f.site == site and f.matches(occurrence):
                    f.fired += 1
                    return f.kind
        return None

    def uniform(self, site: str, lo: float, hi: float) -> float:
        """Deterministic per-(seed, site, draw) uniform — fault parameters
        (delay durations, flip offsets) never consult global RNG state."""
        with self._lock:
            n = self._calls.get(site, 0)
        return random.Random(f"{self.seed}:{site}:{n}").uniform(lo, hi)

    def summary(self) -> dict:
        return {
            "seed": self.seed,
            "faults": [{"spec": f.spec(), "fired": f.fired} for f in self.faults],
        }


# -- process-global arming -------------------------------------------------
_PLAN: Optional[FaultPlan] = None


def arm(plan, *, seed: int = 0) -> FaultPlan:
    """Arm a :class:`FaultPlan` (or a spec string, parsed with ``seed``)
    process-wide.  Returns the armed plan."""
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan, seed=seed)
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def armed() -> bool:
    return _PLAN is not None


def fire(site: str, *, step: Optional[int] = None) -> Optional[str]:
    """The site hook: None when disarmed (the only cost on production
    paths), else the armed plan's decision for this occurrence."""
    if _PLAN is None:
        return None
    return _PLAN.fire(site, step=step)


def uniform(site: str, lo: float, hi: float) -> float:
    if _PLAN is None:
        return lo
    return _PLAN.uniform(site, lo, hi)


@contextlib.contextmanager
def injected(spec: str, *, seed: int = 0):
    """Scoped arming for tests/scenarios: disarms on exit even when the
    body raises (an escaped armed plan would poison later tests)."""
    plan = arm(spec, seed=seed)
    try:
        yield plan
    finally:
        disarm()
