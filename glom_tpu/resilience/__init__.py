"""Resilience subsystem: surviving the failures the telemetry layer detects.

PRs 1-4 built eyes (metrics, forensics, tracing, SLOs); this package turns
detection into survival.  Four pieces, each usable on its own:

  * :mod:`glom_tpu.resilience.faultinject` — seeded, deterministic fault
    injection: a :class:`FaultPlan` parsed from a spec string arms named
    injection sites threaded through the checkpoint writer, the data
    pipeline, and the serving reload watcher.  Zero overhead when
    disarmed (one ``is None`` check per site).
  * :mod:`glom_tpu.resilience.integrity` — checkpoint integrity policy:
    per-array CRCs written next to every artifact
    (:mod:`glom_tpu.checkpoint` computes them at save time and verifies
    on restore), quarantine of corrupt artifacts (renamed ``*.corrupt``,
    counter + ``ckpt_corrupt`` forensics trigger), and
    :func:`latest_valid_step` — the newest checkpoint that VERIFIES,
    which trainer auto-resume, ``denoise.load_checkpoint_state`` and the
    serving hot-reload watcher all fall back to.
  * :mod:`glom_tpu.resilience.supervisor` — a self-healing training
    supervisor: runs ``fit()`` under a restart policy (exponential
    backoff with jitter, crash-loop detection, resume-from-latest-valid
    on every attempt) with restart/giveup counters (split by failure
    reason) in the shared obs registry and a forensics bundle per
    restart.
  * :mod:`glom_tpu.resilience.elastic` — elastic MULTI-HOST semantics on
    top of the supervisor: per-host fault domains (one crash-looping
    host degrades the fleet by one domain, never kills the job),
    heartbeat-based coordinator-loss detection with deterministic
    successor election, and re-planning on device-count change (mesh
    re-derived, params resharded from the last verified checkpoint, the
    exactly-once data cursor re-partitioned).  All clocks injectable;
    all failure paths driven through the seeded fault injector.

``tools/chaos.py`` is the acceptance harness: it runs every named fault
against a tiny CPU train/serve loop and asserts recovery, reporting
per-scenario MTTR.  See ``docs/RESILIENCE.md``.
"""

from glom_tpu.resilience.faultinject import (  # noqa: F401
    FaultError,
    FaultPlan,
    arm,
    armed,
    disarm,
    fire,
    injected,
)
from glom_tpu.resilience.integrity import (  # noqa: F401
    CorruptCheckpointError,
    IntegrityObserver,
    latest_valid_step,
    quarantine,
    verify_artifact,
)
from glom_tpu.resilience.supervisor import (  # noqa: F401
    GiveUp,
    PreemptionError,
    RestartPolicy,
    Supervisor,
    classify_failure,
)
from glom_tpu.resilience.elastic import (  # noqa: F401
    CoordinatorLostError,
    ElasticContext,
    ElasticPlan,
    ElasticSupervisor,
    FaultDomain,
    HeartbeatTracker,
    HostPreemptedError,
    SimClock,
    elect_coordinator,
)
