"""Checkpoint integrity policy: quarantine and newest-valid fallback.

The byte-level mechanism lives in :mod:`glom_tpu.checkpoint` (per-array
CRCs written next to every npz artifact at save time, verified on
restore); this module owns what happens when verification FAILS:

  * :func:`quarantine` — rename the step's artifacts ``*.corrupt`` so no
    later load (and no prune scan) ever considers them again, while the
    bytes stay on disk for post-mortem.
  * :func:`latest_valid_step` — the newest step that verifies, scanning
    newest-first and quarantining failures on the way down.  Trainer
    auto-resume, ``denoise.load_checkpoint_state``, and the serving
    hot-reload watcher all restore from THIS, so a torn write degrades a
    run by one checkpoint interval instead of killing it.
  * :func:`restore_with_fallback` — restore that survives races: a step
    that verified in the scan but fails per-array CRCs at load (bytes
    went bad in between) is quarantined and the next-valid step is tried.
  * :class:`IntegrityObserver` — the telemetry splice: every quarantine
    bumps ``ckpt_corrupt_total`` and fires the debounced ``ckpt_corrupt``
    forensics trigger (one bundle per incident, not one per damaged
    file), matching the trainer's anomaly pipeline.

Steps with no integrity record (pre-resilience checkpoints, orbax/sharded
backends) are presumed good — refusing to load history because it predates
the checksums would turn an upgrade into an outage.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional, Tuple

from glom_tpu import checkpoint as ckpt_lib
from glom_tpu.checkpoint import CorruptCheckpointError  # noqa: F401  (re-export)
from glom_tpu.obs.triggers import TRIGGER_CKPT_CORRUPT

QUARANTINE_SUFFIX = ".corrupt"


class IntegrityObserver:
    """Routes quarantine events into the shared obs stack: counter +
    debounced ``ckpt_corrupt`` trigger + forensics bundle.  All three
    backends are optional — an observer with only a registry still counts.
    ``triggers``/``forensics`` may be attached after construction (the
    serving engine builds them later in its own __init__)."""

    def __init__(self, *, registry=None, triggers=None, forensics=None):
        self.registry = registry
        self.triggers = triggers
        self.forensics = forensics

    def on_corrupt(self, directory: str, step: int, detail: Dict[str, Any]) -> None:
        if self.registry is not None:
            self.registry.counter(
                "ckpt_corrupt_total",
                help="checkpoints quarantined after failing integrity "
                     "verification",
            ).inc()
        if self.forensics is None:
            return
        if self.triggers is not None and not self.triggers.fire(
            TRIGGER_CKPT_CORRUPT, step
        ):
            return  # debounced: one bundle per incident, not per artifact
        detail = dict(detail, directory=directory)
        path = self.forensics.capture(
            TRIGGER_CKPT_CORRUPT, step, detail, trace=False,
        )
        if path is None and self.triggers is not None:
            self.triggers.refund(TRIGGER_CKPT_CORRUPT, step)


def verify_artifact(directory: str, step: int) -> Optional[bool]:
    """Whole-file CRC check against the step's integrity record: True
    (verified), False (corrupt), None (no record — unverifiable, presumed
    good)."""
    return ckpt_lib.verify_file_integrity(directory, step)


def quarantine(
    directory: str, step: int, *,
    observer: Optional[IntegrityObserver] = None,
    reason: str = "",
) -> list:
    """Rename every artifact of ``step`` (npz/orbax/shards + the integrity
    record) to ``<name>.corrupt``.  Quarantined files stop matching the
    checkpoint name patterns, so ``latest_step`` scans, restores, and
    pruning all stop seeing the step — but the evidence stays on disk.
    Best-effort (warns, never raises) and idempotent; returns the list of
    renamed paths."""
    renamed = []
    candidates = [
        ckpt_lib.npz_path(directory, step),
        ckpt_lib._orbax_path(directory, step),
        ckpt_lib.integrity_path(directory, step),
        *ckpt_lib._shard_paths(directory, step),
    ]
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
            renamed.append(path + QUARANTINE_SUFFIX)
        except OSError as e:
            warnings.warn(
                f"failed to quarantine {path} ({type(e).__name__}: {e})",
                stacklevel=2,
            )
    if renamed:
        warnings.warn(
            f"quarantined corrupt checkpoint step {step} in {directory}"
            + (f" ({reason})" if reason else ""),
            stacklevel=2,
        )
        if observer is not None:
            observer.on_corrupt(directory, step, {
                "step": int(step),
                "reason": reason or "integrity verification failed",
                "quarantined": [os.path.basename(p) for p in renamed],
            })
    return renamed


def _candidate_steps(directory: str) -> list:
    """All steps with on-disk artifacts, newest first.  Driven by the
    artifact scan, not the manifest: the manifest only knows the latest
    step, and it may point at exactly the artifact that went bad."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        {s for s in (ckpt_lib._step_of(f) for f in names) if s is not None},
        reverse=True,
    )


def latest_valid_step(
    directory: str, *,
    observer: Optional[IntegrityObserver] = None,
    quarantine_corrupt: bool = True,
    newer_than: Optional[int] = None,
) -> Optional[int]:
    """The newest checkpoint step that verifies, quarantining every newer
    step that fails the whole-file CRC.  Returns None when the directory
    holds no loadable checkpoint at all.

    This is the restore anchor for every resilience consumer: trainer
    auto-resume, the serving engine's initial load and hot-reload watcher,
    and the supervisor's pre-restart sweep.

    The manifest rename is the FINALIZATION BARRIER: no step above the
    manifest's is ever chosen (skipped without even a CRC read, and never
    quarantined).  Two realities force this: a stranded higher artifact
    may be a partial write (a sharded save that crashed between shard
    writes and the manifest rename), and — decisively — an intentional
    ROLLBACK save (manifest moved to a lower step while stale higher
    checkpoints await pruning) must not be silently undone by resuming
    the very step the operator abandoned.  A writer that crashed after
    the artifact but before the rename therefore costs one checkpoint
    interval — the pre-resilience contract, traded for rollback safety.
    Steps at or below the barrier with no integrity record (sharded/orbax
    backends, pre-resilience npz) are presumed good.  An unreadable or
    absent manifest drops the barrier (foreign/legacy dirs still load).

    ``newer_than``: steps at or below it are returned WITHOUT paying the
    file-CRC read — the caller is already serving/holding that step and
    only wants to know nothing newer landed (the hot-reload watcher's
    every-2s poll must not stream a multi-GB artifact each time)."""
    manifest_step = -1  # lazily read: most polls never need it
    for step in _candidate_steps(directory):
        if newer_than is not None and step <= newer_than:
            return step
        if manifest_step == -1:
            manifest_step = ckpt_lib.latest_step(directory)
        if manifest_step is not None and step > manifest_step:
            continue  # above the finalization barrier: never chosen
        ok = verify_artifact(directory, step)
        if ok is False:
            if quarantine_corrupt:
                quarantine(directory, step, observer=observer,
                           reason="file CRC mismatch")
            continue
        return step
    return None


def restore_with_fallback(
    directory: str,
    templates: Dict[str, Any],
    *,
    step: Optional[int] = None,
    per_process: Tuple[str, ...] = (),
    observer: Optional[IntegrityObserver] = None,
) -> Tuple[int, Dict[str, Any]]:
    """``checkpoint.restore`` that survives corruption: with ``step=None``
    each attempt restores the newest VALID step, and a step whose per-array
    CRCs fail at load time (corruption landed between the scan and the
    read) is quarantined and the next one tried.  A pinned ``step`` keeps
    fail-loud semantics — the caller asked for those exact bytes.

    Structural errors (KeyError / shape ValueError: the live pytree differs
    from the saved one) propagate unchanged — falling back to an OLDER
    checkpoint could not fix a code/config mismatch, only hide it."""
    if step is not None:
        return ckpt_lib.restore(directory, templates, step=step,
                                per_process=per_process)
    while True:
        chosen = latest_valid_step(directory, observer=observer)
        if chosen is None:
            raise FileNotFoundError(
                f"no valid checkpoint in {directory} (all candidates "
                f"corrupt or absent)"
            )
        try:
            return ckpt_lib.restore(directory, templates, step=chosen,
                                    per_process=per_process)
        except CorruptCheckpointError as e:
            # each pass quarantines its failure, so the candidate set
            # strictly shrinks — termination is structural
            quarantine(directory, chosen, observer=observer, reason=str(e))
