"""Elastic multi-host training: fault domains, coordinator election,
re-planning on device-count change.

:mod:`glom_tpu.resilience.supervisor` restarts ONE process and assumes the
world comes back the same shape.  At pod scale it does not: preemption and
host churn are the dominant failure mode (arXiv:2204.06514), and a restart
routinely comes back with a *different* topology — a workload-migration
operation, not an error (arXiv:2606.15994).  This module supplies those
semantics, deterministically testable on CPU:

  * **Per-host fault domains** (:class:`FaultDomain`) — every host carries
    its OWN sliding-window failure accounting and backoff arithmetic.  One
    host crash-looping exhausts *its* domain (it is marked dead and the
    job re-plans without it); the survivors' counters never move and the
    job never dies for it while ``min_hosts`` remain.
  * **Heartbeat-based coordinator-loss detection**
    (:class:`HeartbeatTracker`) with **deterministic successor election**
    (:func:`elect_coordinator`: lowest live host id, the lost coordinator
    excluded) — the job outlives the process that was running the
    election.
  * **Re-planning on device-count change** — when a restart attempt comes
    back with fewer (or more) hosts, the mesh is re-derived against
    :func:`glom_tpu.parallel.mesh.elastic_mesh_shape` (data axis absorbs
    the change, model/seq axes preserved), params reshard from the last
    checkpoint that VERIFIES (``integrity.latest_valid_step``), the
    exactly-once data cursor re-partitions (it is a host-count-free global
    position — :class:`glom_tpu.training.data.ElasticBatches`), and
    training RESUMES instead of giving up.

Every decision point is driven through the seeded
:mod:`~glom_tpu.resilience.faultinject` machinery (sites ``host_preempt``
/ ``coordinator_loss`` / ``heartbeat_delay`` / ``shrink_restart``) and
every timestamp flows through an injected clock (:class:`SimClock` for
tests/chaos), so recovery paths replay bit-for-bit.  The module is
stdlib-only; the mesh arithmetic import is lazy and pure.

The driver contract: ``attempt_fn(plan, ctx)`` runs one training attempt
for an :class:`ElasticPlan` and must call ``ctx.tick()`` once per global
step (or iterate a ``ctx.wrap(...)``-wrapped batch stream, which does it)
— the tick is where preemptions strike, heartbeats land, and staleness is
judged.
"""

from __future__ import annotations

import random
import time
import traceback
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from glom_tpu.obs.triggers import (
    TRIGGER_COORDINATOR_LOSS,
    TRIGGER_ELASTIC_REPLAN,
    TRIGGER_HOST_PREEMPT,
)
from glom_tpu.resilience import faultinject, integrity
from glom_tpu.resilience.supervisor import (
    GiveUp,
    PreemptionError,
    RestartPolicy,
    classify_failure,
)


class HostPreemptedError(PreemptionError):
    """One fault domain died (scheduler reclaim, silent worker): the job
    re-plans; only the named host's domain is charged."""

    def __init__(self, host_id: int, step: int = 0, detail: str = ""):
        self.host_id = int(host_id)
        self.step = int(step)
        super().__init__(
            f"host {host_id} preempted at elastic tick {step}"
            + (f" ({detail})" if detail else "")
        )


class CoordinatorLostError(RuntimeError):
    """The coordinator's heartbeat went stale: a successor must be
    elected before the job can continue."""

    def __init__(self, host_id: int, step: int = 0):
        self.host_id = int(host_id)
        self.step = int(step)
        super().__init__(
            f"coordinator host {host_id} heartbeat stale at elastic "
            f"tick {step}"
        )


class SimClock:
    """Deterministic simulation clock for CPU chaos/tests: reading never
    advances time; ``advance``/``sleep`` move it explicitly.  Passed as
    ``clock=``/``sleep=``/``advance=`` so heartbeat-timeout and backoff
    arithmetic replay exactly (and the ``conc-heartbeat-raw-clock`` lint
    rule keeps the production paths honest about using it)."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)

    def sleep(self, s: float) -> None:  # an injected sleep IS a time jump
        self.t += float(s)


def elect_coordinator(hosts: Sequence[int],
                      exclude: Sequence[int] = ()) -> int:
    """Deterministic successor election: the LOWEST live host id not in
    ``exclude`` wins.  No quorum protocol — host liveness is already
    agreed through the fault-domain bookkeeping, so the election only has
    to be a pure function every survivor computes identically."""
    candidates = sorted(set(hosts) - set(exclude))
    if not candidates:
        raise GiveUp("no live host eligible for coordinator election")
    return candidates[0]


@dataclass(frozen=True)
class ElasticPlan:
    """One attempt's topology: which hosts run, who coordinates, and the
    mesh the params reshard onto.  ``resume_step`` is the newest
    checkpoint step that verified at plan time (None = fresh start)."""

    generation: int
    hosts: Tuple[int, ...]
    coordinator: int
    devices_per_host: int
    mesh_shape: Tuple[int, ...]
    resume_step: Optional[int] = None

    @property
    def host_count(self) -> int:
        return len(self.hosts)

    def to_json_dict(self) -> dict:
        return {
            "generation": self.generation,
            "hosts": list(self.hosts),
            "coordinator": self.coordinator,
            "devices_per_host": self.devices_per_host,
            "mesh_shape": list(self.mesh_shape),
            "resume_step": self.resume_step,
        }


class FaultDomain:
    """Per-host failure accounting: ITS sliding window, ITS backoff, ITS
    giveup — the isolation that lets one crash-looping host degrade the
    fleet by exactly one domain instead of taking the job down."""

    def __init__(self, host_id: int, policy: RestartPolicy,
                 rng: random.Random):
        self.host_id = int(host_id)
        self.policy = policy
        self._rng = rng
        self._failures: deque = deque()
        self.failures_total = 0
        self.restarts = 0
        self.steps = 0            # elastic ticks this domain participated in
        self.dead = False         # crash-loop giveup or shrink: never returns
        self.down_until = 0.0     # backoff gate (injected-clock timestamps)
        self.last_reason = ""

    def record_failure(self, now: float, reason: str) -> str:
        """Charge one failure to THIS domain; returns ``"giveup"`` when the
        domain's crash-loop policy exhausts (the domain is marked dead) or
        ``"backoff"`` with ``down_until`` advanced."""
        self._failures.append(now)
        while self._failures and now - self._failures[0] > self.policy.window_s:
            self._failures.popleft()
        self.failures_total += 1
        self.last_reason = reason
        if len(self._failures) >= self.policy.max_failures:
            self.dead = True
            return "giveup"
        delay = self.policy.backoff_s(self.restarts, self._rng)
        self.restarts += 1
        self.down_until = now + delay
        return "backoff"

    def available(self, now: float) -> bool:
        return not self.dead and now >= self.down_until


class HeartbeatTracker:
    """Last-beat table under an injected clock.  ``stale`` is the ONLY
    judgment: a host that misses beats for longer than ``timeout_s`` is
    presumed dead — delayed beats inside the window (the
    ``heartbeat_delay`` fault) must never eject anyone."""

    def __init__(self, timeout_s: float, clock: Callable[[], float]):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self._last: Dict[int, float] = {}

    def reset(self, hosts: Sequence[int]) -> None:
        """(Re)arm the table for an attempt's host set: every host is
        credited a beat NOW, so backoff time never counts as staleness."""
        now = self._clock()
        self._last = {int(h): now for h in hosts}

    def beat(self, host: int) -> None:
        self._last[int(host)] = self._clock()

    def age(self, host: int) -> float:
        return self._clock() - self._last[int(host)]

    def stale(self, host: int) -> bool:
        return self.age(host) > self.timeout_s


class ElasticContext:
    """Per-attempt handle: ``tick()`` once per global step is where the
    simulation's physics happen — fault sites fire, surviving hosts beat,
    staleness is judged, per-domain step cadence advances, and the first
    tick after a failure closes the MTTR measurement."""

    def __init__(self, supervisor: "ElasticSupervisor", plan: ElasticPlan):
        self._sup = supervisor
        self.plan = plan
        self.ticks = 0
        self._silenced: set = set()
        self._mttr_closed = False
        supervisor._tracker.reset(plan.hosts)

    # -- victim selection (deterministic, documented) ----------------------
    def _victim(self) -> int:
        """The highest-id live non-coordinator host; the coordinator only
        when it is the sole survivor.  A fixed rule, so a ``*COUNT`` spec
        hits the SAME host repeatedly — exactly the crash-loop shape the
        per-domain policy exists for."""
        workers = [h for h in self.plan.hosts
                   if h != self.plan.coordinator and h not in self._silenced]
        if workers:
            return max(workers)
        return self.plan.coordinator

    def tick(self, step: Optional[int] = None) -> None:
        sup = self._sup
        self.ticks += 1
        sup.ticks_total += 1
        tick_id = step if step is not None else sup.ticks_total
        if sup._advance is not None and sup.step_dt:
            sup._advance(sup.step_dt)
        now = sup._clock()

        delayed: set = set()
        if faultinject.fire("heartbeat_delay") is not None:
            delayed.add(self._victim())
        if faultinject.fire("coordinator_loss") is not None:
            # the coordinator goes SILENT (not a clean crash): nothing is
            # raised here — detection must come from heartbeat staleness
            self._silenced.add(self.plan.coordinator)
        if faultinject.fire("host_preempt") is not None:
            victim = self._victim()
            self._silenced.add(victim)
            raise HostPreemptedError(victim, step=tick_id,
                                     detail="injected preemption")

        for h in self.plan.hosts:
            if h in self._silenced:
                continue  # a silent host neither beats nor steps
            if h not in delayed:
                sup._tracker.beat(h)
            sup.domains[h].steps += 1
        for h in self.plan.hosts:
            if sup._tracker.stale(h):
                if h == self.plan.coordinator:
                    raise CoordinatorLostError(h, step=tick_id)
                raise HostPreemptedError(
                    h, step=tick_id, detail="heartbeat stale"
                )
        if not self._mttr_closed and sup._last_failure_t is not None:
            # the attempt's first tick COMPLETED (fault sites fired clean,
            # beats landed, nobody stale): service is restored — close the
            # MTTR measurement.  Deliberately at the END of the tick: an
            # attempt that dies again on its very first tick has restored
            # nothing and must extend the same outage.
            mttr = max(now - sup._last_failure_t, 0.0)
            sup.mttr_s.append(mttr)
            sup._last_failure_t = None
            if sup.registry is not None:
                sup.registry.gauge(
                    "elastic_mttr_s",
                    help="injected-clock seconds from the last failure to "
                         "the first completed post-restart step",
                    unit="seconds",
                ).set(mttr)
        self._mttr_closed = True

    def wrap(self, stream, record: Optional[list] = None):
        """Wrap a batch iterator so every draw ticks this context first
        (a preemption therefore strikes BEFORE the batch is consumed and
        the cursor never advances past it).  ``record`` collects the
        global sample slots actually CONSUMED (from the stream's
        consumer-exact cursor deltas) — the exactly-once evidence the
        acceptance tests audit."""
        return _TickedStream(self, stream, record)


class _TickedStream:
    """Iterator shim: tick-then-draw, cursor forwarding, consumed-slot
    recording.  State methods delegate to the inner stream so the trainer
    checkpoints the cursor exactly as if the shim were not there."""

    def __init__(self, ctx: ElasticContext, inner, record: Optional[list]):
        self._ctx = ctx
        self._inner = inner
        self._record = record
        self._stateful = hasattr(inner, "state_dict")
        self._prev = self._cursor()

    def _cursor(self) -> Optional[int]:
        if not self._stateful:
            return None
        state = self._inner.state_dict()
        consumed = state.get("consumed")
        return int(consumed) if consumed is not None else None

    def __iter__(self):
        return self

    def __next__(self):
        self._ctx.tick()
        item = next(self._inner)
        if self._record is not None:
            cur = self._cursor()
            if cur is not None and self._prev is not None:
                self._record.extend(range(self._prev, cur))
            self._prev = cur
        return item

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, state):
        self._inner.load_state_dict(state)
        self._prev = self._cursor()  # a restored cursor is a new baseline

    def close(self):
        close = getattr(self._inner, "close", None)
        if callable(close):
            close()


class ElasticSupervisor:
    """Coordinator/worker supervisor with per-host fault domains.

    ``attempt_fn(plan, ctx)`` runs one training attempt and must tick the
    context once per global step.  The run loop converts failures into
    re-plans:

    * :class:`HostPreemptedError` — the named domain is charged (its own
      window/backoff); a domain whose crash-loop policy exhausts is marked
      dead and the job re-plans WITHOUT it (degraded, not dead).  A
      preempted domain whose backoff fits inside ``rejoin_grace_s`` is
      waited for (full-fleet restart); otherwise the restart proceeds
      degraded and the host rejoins at the next re-plan it is up for.
    * :class:`CoordinatorLostError` — a successor is elected
      (:func:`elect_coordinator`, the lost coordinator excluded) and the
      lost host is charged like a preemption.
    * any other exception — a JOB-level failure (code/data bug: no single
      domain to blame) under its own sliding-window ``job_policy``.

    Every re-plan fires the ``shrink_restart`` fault site (a seeded plan
    can make the failed host never return, or a new host appear), derives
    the mesh from the surviving host count, anchors ``resume_step`` on
    ``integrity.latest_valid_step``, and — when the host count changed —
    writes a ``elastic_replan`` forensics bundle with the before/after
    plans and the checkpointed data cursor.  ``GiveUp`` when fewer than
    ``min_hosts`` domains remain.  All clocks/sleeps/jitter are
    injectable; with :class:`SimClock` the whole recovery history is a
    deterministic function of (spec, seed).
    """

    def __init__(
        self,
        attempt_fn: Callable[[ElasticPlan, ElasticContext], Any],
        *,
        hosts: int = 2,
        devices_per_host: int = 1,
        policy: Optional[RestartPolicy] = None,
        job_policy: Optional[RestartPolicy] = None,
        min_hosts: int = 1,
        heartbeat_timeout_s: float = 5.0,
        rejoin_grace_s: float = 1.0,
        step_dt: float = 0.0,
        checkpoint_dir: Optional[str] = None,
        registry=None,
        forensics=None,
        observer: Optional[integrity.IntegrityObserver] = None,
        mesh_shape_fn: Optional[Callable[[int, int], tuple]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        advance: Optional[Callable[[float], None]] = None,
        seed: int = 0,
    ):
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        if min_hosts < 1 or min_hosts > hosts:
            raise ValueError(
                f"min_hosts must be in [1, {hosts}], got {min_hosts}"
            )
        self.attempt_fn = attempt_fn
        self.policy = policy if policy is not None else RestartPolicy()
        self.job_policy = (job_policy if job_policy is not None
                           else self.policy)
        self.devices_per_host = int(devices_per_host)
        self.min_hosts = int(min_hosts)
        self.rejoin_grace_s = float(rejoin_grace_s)
        self.step_dt = float(step_dt)
        self.checkpoint_dir = checkpoint_dir
        self.registry = registry
        self.forensics = forensics
        self.observer = observer if observer is not None else (
            integrity.IntegrityObserver(registry=registry,
                                        forensics=forensics)
        )
        self._clock = clock
        self._sleep = sleep
        self._advance = advance
        self._rng = random.Random(seed)
        self._mesh_shape_fn = mesh_shape_fn
        self._tracker = HeartbeatTracker(heartbeat_timeout_s, clock)
        self.domains: Dict[int, FaultDomain] = {
            h: FaultDomain(h, self.policy,
                           random.Random((seed << 8) ^ (h + 1)))
            for h in range(hosts)
        }
        self._job_failures: deque = deque()
        self.plan: Optional[ElasticPlan] = None
        self.context: Optional[ElasticContext] = None
        self.generation = 0
        self.restarts = 0
        self.elections = 0
        self.replans = 0           # re-plans where the host count CHANGED
        self.ticks_total = 0
        self.mttr_s: List[float] = []
        self._last_failure_t: Optional[float] = None

    # -- telemetry ---------------------------------------------------------
    def _count(self, name: str, help: str) -> None:
        if self.registry is not None:
            self.registry.counter(name, help=help).inc()

    def _gauge(self, name: str, value: float, help: str = "") -> None:
        if self.registry is not None:
            self.registry.gauge(name, help=help).set(value)

    def _bundle(self, trigger: str, step: int, detail: dict) -> None:
        """Direct forensics capture (no debounce): every elastic incident
        is a distinct event and the domain policies bound the count."""
        if self.forensics is not None:
            self.forensics.capture(trigger, step, detail, trace=False)

    # -- planning ----------------------------------------------------------
    def _mesh_shape(self, host_count: int) -> tuple:
        if self._mesh_shape_fn is not None:
            return tuple(self._mesh_shape_fn(host_count,
                                             self.devices_per_host))
        from glom_tpu.parallel.mesh import elastic_mesh_shape

        return elastic_mesh_shape(host_count, self.devices_per_host)

    def _cursor_detail(self, resume_step: Optional[int]) -> Optional[dict]:
        """Best-effort read of the checkpointed data cursor for the
        re-plan evidence (the bundle must show the position the restarted
        stream will resume from)."""
        if self.checkpoint_dir is None or resume_step is None:
            return None
        from glom_tpu import checkpoint as ckpt_lib

        try:
            tree = ckpt_lib.load_tree(self.checkpoint_dir, resume_step,
                                      "data")
        except (OSError, KeyError, ValueError):
            return None  # no cursor in this checkpoint: stateless stream
        return {k: int(v) for k, v in tree.items()}

    def _replan(self, *, reason: str, failed: Optional[int],
                exclude_coordinator: Sequence[int] = ()) -> ElasticPlan:
        prev = self.plan
        # the shrink/grow site models "the restart after a HOST failure
        # came back with a different fleet": it only fires when a failed
        # host is named — the initial plan and job-level-failure replans
        # must not consume an occurrence with no effect (a spec's shrink
        # would silently vanish into e.g. an earlier data:crash restart)
        kind = (faultinject.fire("shrink_restart")
                if prev is not None and failed is not None else None)
        if kind == "shrink" and failed is not None:
            # the restart comes back with FEWER hosts: the failed one is
            # gone for good (its capacity was reclaimed, not rebooted)
            self.domains[failed].dead = True
        elif kind == "grow":
            new_id = max(self.domains) + 1
            self.domains[new_id] = FaultDomain(
                new_id, self.policy,
                random.Random((self._rng.randrange(1 << 30) << 8)
                              ^ (new_id + 1)))
        now = self._clock()
        # wait out backoffs short enough to be worth a full-fleet restart;
        # longer ones restart degraded (elasticity over completeness)
        waitable = [d.down_until - now for d in self.domains.values()
                    if not d.dead and now < d.down_until
                    and d.down_until - now <= self.rejoin_grace_s]
        if waitable:
            self._sleep(max(waitable))
            now = self._clock()
        live = sorted(h for h, d in self.domains.items() if d.available(now))
        if len(live) < self.min_hosts:
            raise GiveUp(
                f"{len(live)} live fault domain(s) < min_hosts="
                f"{self.min_hosts} after {reason!r} (dead: "
                f"{sorted(h for h, d in self.domains.items() if d.dead)})"
            )
        if (prev is not None and prev.coordinator in live
                and prev.coordinator not in exclude_coordinator):
            coordinator = prev.coordinator  # sticky: elections are churn
        else:
            coordinator = elect_coordinator(live,
                                            exclude=exclude_coordinator)
            if prev is not None and coordinator != prev.coordinator:
                self.elections += 1
                self._count("elastic_elections_total",
                            "coordinator successor elections")
        resume_step = None
        if self.checkpoint_dir is not None:
            resume_step = integrity.latest_valid_step(
                self.checkpoint_dir, observer=self.observer
            )
        self.generation += 1
        plan = ElasticPlan(
            generation=self.generation,
            hosts=tuple(live),
            coordinator=coordinator,
            devices_per_host=self.devices_per_host,
            mesh_shape=self._mesh_shape(len(live)),
            resume_step=resume_step,
        )
        self._gauge("elastic_hosts", len(live),
                    help="live fault domains in the current plan")
        self._gauge("elastic_generation", self.generation,
                    help="elastic plan generation")
        if prev is not None and plan.host_count != prev.host_count:
            self.replans += 1
            self._count("elastic_replans_total",
                        "re-plans where the host count changed (mesh "
                        "re-derived, params resharded, cursor "
                        "re-partitioned)")
            self._bundle(TRIGGER_ELASTIC_REPLAN, self.ticks_total, {
                "reason": reason,
                "previous_plan": prev.to_json_dict(),
                "new_plan": plan.to_json_dict(),
                "data_cursor": self._cursor_detail(resume_step),
            })
        self.plan = plan
        return plan

    # -- failure bookkeeping ----------------------------------------------
    def _on_domain_failure(self, host_id: int, reason: str,
                           exc: BaseException, trigger: str) -> None:
        now = self._clock()
        self._last_failure_t = now
        domain = self.domains[host_id]
        outcome = domain.record_failure(now, reason)
        self.restarts += 1
        self._count("elastic_restarts_total",
                    "elastic attempt restarts (any reason)")
        if self.registry is not None:
            self.registry.counter(
                self.registry.labeled("elastic_restarts_", reason),
                help="elastic restarts split by failure reason",
            ).inc()
            self.registry.counter(
                self.registry.labeled("elastic_domain_failures_h", host_id),
                help="failures charged to one fault domain",
            ).inc()
        if reason == "preempt":
            self._count("elastic_preemptions_total",
                        "fault-domain preemptions survived")
        if outcome == "giveup":
            self._count("elastic_domain_giveups_total",
                        "fault domains marked dead by their own "
                        "crash-loop policy")
        self._bundle(trigger, self.ticks_total, {
            "host": host_id,
            "reason": reason,
            "outcome": outcome,
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)),
            "domain_failures_in_window": len(domain._failures),
            "domain_restarts": domain.restarts,
            "plan": self.plan.to_json_dict() if self.plan else None,
        })

    def _on_job_failure(self, exc: BaseException) -> str:
        now = self._clock()
        self._last_failure_t = now
        self._job_failures.append(now)
        while (self._job_failures
               and now - self._job_failures[0] > self.job_policy.window_s):
            self._job_failures.popleft()
        reason = classify_failure(exc)
        self.restarts += 1
        self._count("elastic_restarts_total",
                    "elastic attempt restarts (any reason)")
        if self.registry is not None:
            self.registry.counter(
                self.registry.labeled("elastic_restarts_", reason),
                help="elastic restarts split by failure reason",
            ).inc()
        if len(self._job_failures) >= self.job_policy.max_failures:
            raise GiveUp(
                f"giving up after {len(self._job_failures)} job-level "
                f"failures within {self.job_policy.window_s:.0f}s (last: "
                f"{type(exc).__name__}: {exc})"
            ) from exc
        self._sleep(self.job_policy.backoff_s(
            len(self._job_failures) - 1, self._rng))
        return reason

    # -- the loop ----------------------------------------------------------
    def run(self) -> Any:
        plan = self._replan(reason="initial", failed=None)
        while True:
            ctx = ElasticContext(self, plan)
            self.context = ctx
            try:
                result = self.attempt_fn(plan, ctx)
            except (KeyboardInterrupt, SystemExit):
                raise  # operator intent, never a restartable failure
            except CoordinatorLostError as e:
                self._on_domain_failure(e.host_id, "coordinator_loss", e,
                                        TRIGGER_COORDINATOR_LOSS)
                plan = self._replan(reason="coordinator_loss",
                                    failed=e.host_id,
                                    exclude_coordinator=(e.host_id,))
            except PreemptionError as e:
                host_id = getattr(e, "host_id", None)
                if host_id is None:
                    # a bare PreemptionError carries no host attribution
                    # (production code raising the exported base directly,
                    # e.g. a SIGTERM handler): charging any single domain —
                    # least of all the coordinator — would mark a healthy
                    # host dead; it is a JOB-level event
                    reason = self._on_job_failure(e)
                    plan = self._replan(reason=reason, failed=None)
                else:
                    self._on_domain_failure(host_id, "preempt", e,
                                            TRIGGER_HOST_PREEMPT)
                    plan = self._replan(reason="preempt", failed=host_id)
            except Exception as e:
                reason = self._on_job_failure(e)  # raises GiveUp at limit
                plan = self._replan(reason=reason, failed=None)
            else:
                self._gauge("elastic_hosts", plan.host_count,
                            help="live fault domains in the current plan")
                return result
