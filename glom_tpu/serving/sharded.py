"""Mesh-sharded serving: placement rules + kernel dispatch for the engine.

The `MULTICHIP_r05` dry-run proves the ``parallel/`` stack (DP/TP/SP +
expert sharding) matches sequential execution; this module is the seam
that brings it into the REQUEST path.  An engine built with a
``mesh_shape`` AOT-compiles every bucket with explicit in/out shardings
(the ``parallel/inference.py`` recipe, generalized to sharded params), so
a model too large for one chip serves TP-sharded with zero request-path
compiles — the same bucketed-executable contract as the single-device
engine, just partitioned.

Three concerns live here, shared by startup and every hot reload:

  * **mesh resolution** — :func:`resolve_mesh` builds a
    ``parallel/mesh.py`` mesh over the FIRST ``prod(mesh_shape)``
    devices (a serving replica may deliberately own a subset of a host's
    chips; training's make_mesh covers all of them);
  * **placement** — :func:`param_shardings` turns the training-side
    pspec rules (``parallel/sharding.py``) into a NamedSharding tree
    matching the QUANTIZED param tree: int8 leaves become
    ``{int8_q, int8_scale}`` records whose specs are derived from the
    original weight's spec with any axis that no longer divides its dim
    dropped (a ``(g, 1, d)`` scale can't shard a size-1 dim — it rides
    replicated, which is exactly right for a bandwidth-trivial scale);
  * **kernel dispatch** — :func:`resolve_sharded_kernels` mirrors the
    Trainer's rule: ``ff_impl='fused'`` runs the single-launch kernel via
    ``parallel/fused_shard.py`` under pure-DP meshes only, and
    warns + falls back to the shard_mapped unfused pair
    (``parallel/ff_shard.py``) on TP/EP/seq meshes, where the one-shot
    consensus and whole-net weight blocks are structurally incompatible.

Everything here is host-side setup (runs once at engine build / reload);
the request path still only calls pre-compiled executables.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from glom_tpu.config import GlomConfig

PARAM_SHARDINGS = ("replicated", "tp", "ep")


def resolve_mesh(
    mesh_shape: Sequence[int],
    axis_names: Sequence[str] = ("data", "model", "seq"),
) -> Mesh:
    """A serving mesh over the first ``prod(mesh_shape)`` local devices.

    Unlike training's :func:`glom_tpu.parallel.mesh.make_mesh` (which must
    cover every device), a serving replica may own a SUBSET of the host's
    chips — e.g. two 4-chip replicas on one 8-chip host — so the mesh is
    built over exactly the devices the shape names."""
    from glom_tpu.parallel.mesh import make_mesh

    shape = tuple(int(s) for s in mesh_shape)
    if any(s < 1 for s in shape):
        raise ValueError(f"mesh_shape entries must be >= 1, got {shape}")
    n = int(np.prod(shape))
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"mesh_shape {shape} needs {n} devices; only "
            f"{len(devices)} available"
        )
    return make_mesh(shape, tuple(axis_names), devices=devices[:n])


def mesh_axes_dict(mesh: Optional[Mesh]) -> Optional[dict]:
    """``{"data": 4, "model": 2, ...}`` — the /healthz + snapshot label."""
    if mesh is None:
        return None
    return {name: int(size) for name, size in mesh.shape.items()}


def _sanitize_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Drop any spec axis that does not evenly divide its dim.

    The ONE rule that makes the training pspecs safe to reuse on
    quantized trees: an int8 scale's contracted dim is 1, so the weight's
    model-axis entry stops dividing and is dropped (the scale replicates);
    a genuinely mis-sized weight would likewise fall back loudly rather
    than fail deep inside GSPMD."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, entry in zip(shape, entries[: len(shape)]):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        out.append(entry if size > 0 and dim % size == 0 else None)
    return P(*out)


def _lookup(spec_tree, path) -> Optional[P]:
    """Walk a plain-dict pspec tree by a jax key path; None when the path
    leaves the tree (an unexpected leaf rides replicated)."""
    node = spec_tree
    for key in path:
        name = getattr(key, "key", None)
        if not isinstance(node, dict) or name not in node:
            return None
        node = node[name]
    return node if isinstance(node, P) else None


def param_shardings(
    mesh: Mesh,
    config: GlomConfig,
    quantized_params,
    *,
    param_sharding: str = "replicated",
    model_axis: str = "model",
) -> object:
    """NamedSharding tree matching ``quantized_params`` (the engine's
    ``{"glom": ..., "decoder": ...}`` host tree AFTER
    :func:`glom_tpu.serving.quant.quantize_tree`).

    The glom subtree follows the training placement rules
    (``parallel.sharding.param_pspecs`` for tp,
    ``level_sharded_pspecs`` for ep); the decoder (tiny) and any leaf the
    rules don't name are replicated.  Each spec is sanitized against the
    ACTUAL leaf shape, so int8 ``{int8_q, int8_scale}`` records inherit
    the weight's spec where it still divides and replicate where it
    doesn't."""
    if param_sharding not in PARAM_SHARDINGS:
        raise ValueError(
            f"unknown param_sharding {param_sharding!r}; "
            f"one of {PARAM_SHARDINGS}"
        )
    from glom_tpu.parallel import sharding as rules

    if param_sharding == "tp":
        glom_specs = rules.param_pspecs(config, model_axis=model_axis)
    elif param_sharding == "ep":
        glom_specs = rules.level_sharded_pspecs(
            config, axis_size=int(mesh.shape[model_axis]),
            model_axis=model_axis,
        )
    else:
        glom_specs = {}
    spec_tree = {"glom": glom_specs}

    def one(path, leaf):
        arr = np.asarray(leaf)
        # int8 records sit one dict level BELOW the weight's spec: strip
        # the record key so int8_q/int8_scale both resolve the weight spec
        lookup_path = path
        tail = getattr(path[-1], "key", None) if path else None
        if tail in ("int8_q", "int8_scale"):
            lookup_path = path[:-1]
        spec = _lookup(spec_tree, lookup_path) or P()
        return NamedSharding(mesh, _sanitize_spec(spec, arr.shape, mesh))

    return jax.tree_util.tree_map_with_path(one, quantized_params)


def batch_shardings(mesh: Mesh, *, data_axis: str = "data"):
    """``(img_sharding, out_sharding)`` for the endpoint forwards: images
    and every per-image output shard their leading batch axis over
    ``data_axis`` (a trailing-axes P entry would over-constrain — GSPMD
    lays the rest out itself)."""
    sh = NamedSharding(mesh, P(data_axis))
    return sh, sh


def validate_buckets(buckets: Sequence[int], mesh: Mesh,
                     *, data_axis: str = "data") -> None:
    """Every bucket must divide over the data axis: a 4-way data-sharded
    executable for bucket 2 cannot exist, and failing here names the fix
    (pick buckets that are multiples) instead of erroring mid-warmup."""
    n_data = int(mesh.shape[data_axis])
    if n_data <= 1:
        return
    bad = [b for b in buckets if b % n_data]
    if bad:
        raise ValueError(
            f"buckets {bad} are not divisible by the mesh's data axis "
            f"({data_axis}={n_data}); every bucket must be a multiple so "
            f"each device holds an equal batch shard"
        )


def resolve_sharded_kernels(
    mesh: Mesh,
    config: GlomConfig,
    *,
    param_sharding: str = "replicated",
    data_axis: str = "data",
    model_axis: str = "model",
    seq_axis: str = "seq",
):
    """``(ff_fn, fused_fn)`` for :func:`glom_tpu.models.glom.apply` under
    this mesh — the Trainer's dispatch rule, reused for serving:

      * dense FF: ``(None, None)`` — GSPMD shards plain matmuls natively;
      * ``ff_impl='fused'`` on a pure-DP mesh with the shape supported:
        the single-launch kernel via ``parallel.fused_shard`` (params
        replicated, batch sharded);
      * ``ff_impl='pallas'``, or ``'fused'`` on a TP/EP/seq-sharded mesh
        (structurally incompatible — warn): the shard_mapped unfused
        pallas FF via ``parallel.ff_shard``, matching the actual param
        placement so ``pallas_call``'s GSPMD opacity can't silently
        all-gather the shards."""
    if mesh.devices.size <= 1 or config.ff_impl not in ("pallas", "fused"):
        return None, None
    from glom_tpu.models.glom import fused_update_supported

    seq_sharded = int(mesh.shape.get(seq_axis, 1)) > 1
    params_sharded = (param_sharding != "replicated"
                      and int(mesh.shape[model_axis]) > 1)
    if (config.ff_impl == "fused" and fused_update_supported(config)
            and not seq_sharded and not params_sharded):
        from glom_tpu.parallel.fused_shard import make_sharded_fused_update

        return None, make_sharded_fused_update(
            mesh, config, data_axis=data_axis,
        )
    if config.ff_impl == "fused":
        warnings.warn(
            "serving ff_impl='fused' does not support this mesh (seq- or "
            "model-sharded, or supports_config failed); falling back to "
            "the sharded unfused pallas FF",
            stacklevel=2,
        )
    from glom_tpu.parallel.ff_shard import make_sharded_ff_pallas

    ff_fn = make_sharded_ff_pallas(
        mesh, param_sharding=param_sharding, data_axis=data_axis,
        model_axis=model_axis,
        seq_axis=seq_axis if seq_sharded else None,
        fused_bwd=config.ff_fused_bwd,
    )
    return ff_fn, None
