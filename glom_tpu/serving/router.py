"""Replica fleet router: one front door over N serving engines.

Within a replica, :mod:`glom_tpu.serving.sharded` scales the MODEL (mesh-
sharded buckets); this module scales THROUGHPUT: a stdlib HTTP front that
dispatches ``/embed`` / ``/reconstruct`` / ``/session/*`` across N
independent engine replicas — the TPU serving playbook (arXiv:2204.06514, the Gemma serving
comparison arXiv:2605.25645): shard within a slice for size, replicate
across slices for load.

**Dispatch** is least-loaded (fewest in-flight proxied requests, ties
rotated round-robin) unless the request carries an ``X-Affinity-Key``
header, which routes on a consistent-hash ring (64 vnodes/replica) so a
client's related requests land on one replica (warm session state, stable
tail latency) while the ring redistributes only the failed replica's keys
on ejection.

**Health**: a probe loop GETs each replica's ``/healthz`` every
``health_interval_s``.  ``eject_after`` consecutive failures — probe
failures and request-path connection errors count alike — ejects the
replica from dispatch; probes continue at exponentially backed-off
intervals and a passing probe re-admits it (after a version catch-up when
the fleet rolled forward while it was gone).  A request that hits a dead
replica fails over to the next healthy one; only a fleet with zero
healthy replicas answers 503.

**Coordinated rollout** (no half-old/half-new fleet): hot reload across
replicas is a staged two-phase swap driven through the engines'
``/admin/reload/*`` API —

  1. *prepare*: every healthy replica loads + places the SAME pinned
     checkpoint step off its request path; any failure aborts the
     rollout with every replica still serving the old step;
  2. *commit*: the router briefly gates dispatch (in-flight requests
     finish on old params; new arrivals queue), then commits every
     replica's one-reference swap; a commit failure rolls the already-
     committed replicas back before the gate reopens.

The gate gives the observable guarantee tested in
``tests/test_router.py``: ordered by dispatch time, responses never go
new-step -> old-step — a client can never read version N and then be
served version N-1 by a later request.

**Observability**: the router runs the same tracing/metrics stack as the
engine.  Every request gets a ``router_request`` root span with ``route``
and per-attempt ``proxy`` children; the forwarded ``traceparent`` carries
the proxy span's id, so the engine's ``request`` span parents under it
and ``tools/trace_report.py`` shows the whole hop.  ``/metrics`` serves
the router's own families plus every replica's families relabeled with
``replica="<name>"``; ``/healthz`` aggregates per-replica state and the
model's input contract (``tools/loadgen.py`` reads the router exactly
like a single engine).  ``/debug/traces`` (completed-trace ring) and
``/debug/timeline`` (bounded ejection/re-admission/rollout event ring +
rollout state-machine position) are the pull plane the fleet observatory
(:mod:`glom_tpu.obs.observatory`) stitches and correlates.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from glom_tpu.obs import MetricRegistry
from glom_tpu.obs.events import Timeline
from glom_tpu.obs.exporters import (
    OPENMETRICS_CONTENT_TYPE,
    PROM_TEXT_CONTENT_TYPE,
    prometheus_lines,
    wants_openmetrics,
)
from glom_tpu.obs.tracing import (
    SPAN_PARSE,
    SPAN_PROXY,
    SPAN_RESPOND,
    SPAN_ROUTE,
    SPAN_ROUTER_REQUEST,
    TraceSink,
    Tracer,
    debug_traces_payload,
    format_traceparent,
    parse_traceparent,
    request_trace_id,
)

ENDPOINTS = ("embed", "reconstruct", "parse")
# proxied POST routes: the stateless pair plus the stateful session
# endpoints.  Session requests SHOULD carry ``X-Affinity-Key: <session
# id>`` — the consistent-hash ring then pins the whole stream to one
# replica, where its column state is resident (the router never parses
# request bodies to recover the id: body parsing on the proxy hot path
# would tax every request for the session feature).  Without the header a
# session still WORKS — least-loaded dispatch just scatters its frames,
# and each replica that sees one cold-settles (correct, but the warm-
# start savings are lost).  On ejection the ring moves only the dead
# replica's keys: those sessions cold-restart on their new replica — the
# documented cold-restart contract (docs/SERVING.md).
#
# /parse rides the same single-replica proxy as the stateless pair and
# /session/parse the same affinity rules as /session/embed.  /similar is
# the odd one out: it FANS OUT to every healthy replica (each may hold a
# different index shard family) and merges the per-image top-k here —
# see similar_fanout for the deterministic merge rule.
ROUTED_PATHS = ("/embed", "/reconstruct", "/parse", "/similar",
                "/session/embed", "/session/parse", "/session/reset")
_VNODES = 64
_HEX_ID = re.compile(r"[0-9a-f]{1,32}")
# one Prometheus sample line: name[{labels}] value [timestamp]
_SAMPLE_RE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?( .+)$")


class NoHealthyReplica(RuntimeError):
    """Every replica is ejected (or the dispatch gate timed out)."""


def _default_http(method: str, url: str, body: Optional[bytes],
                  headers: Dict[str, str], timeout: float
                  ) -> Tuple[int, Dict[str, str], bytes]:
    """The one HTTP client (stdlib), injectable for deterministic tests.
    Returns ``(status, headers, body)`` for ANY HTTP status — a replica's
    4xx/5xx is a valid answer to pass through, not a transport failure;
    only connection-level errors raise (URLError/OSError)."""
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers.items()), r.read()
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, dict(e.headers.items()), payload


class Replica:
    """One engine replica's routing state (mutated under the router lock)."""

    __slots__ = ("name", "url", "healthy", "inflight", "fail_streak",
                 "next_probe_at", "step", "requests", "errors", "ejections",
                 "last_health")

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.healthy = True       # optimistic: first probe/request corrects
        self.inflight = 0
        self.fail_streak = 0
        self.next_probe_at = 0.0  # monotonic deadline for the next probe
        self.step: Optional[int] = None
        self.requests = 0
        self.errors = 0
        self.ejections = 0
        self.last_health: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "url": self.url, "healthy": self.healthy,
            "inflight": self.inflight, "step": self.step,
            "fail_streak": self.fail_streak, "requests": self.requests,
            "errors": self.errors, "ejections": self.ejections,
        }


class FleetRouter:
    """Dispatch + health + coordinated-rollout brain (transport-agnostic:
    the HTTP front below is one thin consumer; tests drive the methods
    directly with an injected clock and http fn)."""

    def __init__(
        self,
        replica_urls: Sequence[str],
        *,
        names: Optional[Sequence[str]] = None,
        health_interval_s: float = 1.0,
        health_timeout_s: float = 5.0,
        eject_after: int = 2,
        probe_backoff_max: int = 8,
        request_timeout_s: float = 60.0,
        admin_timeout_s: float = 120.0,
        commit_timeout_s: float = 10.0,
        gate_timeout_s: float = 30.0,
        rollout_poll_s: float = 0.0,
        drain_timeout_s: float = 10.0,
        registry: Optional[MetricRegistry] = None,
        clock=None,
        sleep=None,
        http=None,
        trace_log: Optional[str] = None,
        trace_max_traces: int = 256,
        capacity_policy: Optional[str] = None,
        capacity_persist_windows: int = 5,
    ):
        if not replica_urls:
            raise ValueError("need at least one replica URL")
        names = list(names) if names else [
            f"r{i}" for i in range(len(replica_urls))]
        if len(names) != len(replica_urls):
            raise ValueError("names and replica_urls must align")
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        if eject_after < 1:
            raise ValueError(f"eject_after must be >= 1, got {eject_after}")
        self.replicas: List[Replica] = [
            Replica(n, u) for n, u in zip(names, replica_urls)
        ]
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.eject_after = eject_after
        self.probe_backoff_max = max(1, probe_backoff_max)
        self.request_timeout_s = request_timeout_s
        self.admin_timeout_s = admin_timeout_s
        # the GATED phase's per-call bound: while the dispatch gate is
        # closed every client is waiting, so a hung replica's commit must
        # fail fast (<< gate_timeout_s) instead of riding the generous
        # prepare-phase admin timeout into a fleet-wide 503
        self.commit_timeout_s = commit_timeout_s
        self.gate_timeout_s = gate_timeout_s
        self.rollout_poll_s = rollout_poll_s
        self.drain_timeout_s = drain_timeout_s
        self.registry = registry if registry is not None else MetricRegistry()
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self._http = http if http is not None else _default_http
        self._lock = threading.Lock()          # replica state + rr counter
        self._rollout_lock = threading.Lock()  # one rollout at a time
        self._rr = 0
        self.fleet_step: Optional[int] = None  # last coordinated commit
        # -- event timeline (pulled via /debug/timeline) -------------------
        # bounded ring of the fleet's state transitions — ejections,
        # re-admissions, rollout phase outcomes — each with a monotone
        # seq so the observatory reads incrementally and correlates them
        # with replica-side forensics into one incident bundle.  The ring
        # is the shared typed Timeline (obs.events): its own leaf lock,
        # so note_event never acquires another lock and is safely
        # callable from under _lock or _rollout_lock.
        self._timeline = Timeline(maxlen=256, clock=self._clock)
        # coarse rollout-state-machine position for the fleet console
        # (plain str store/load — no lock needed for a telemetry read)
        self.rollout_phase = "idle"
        # the commit gate: cleared only for the (short) commit phase of a
        # rollout; handler threads wait on it before picking a replica
        self._dispatch_open = threading.Event()
        self._dispatch_open.set()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        trace_exporter = None
        if trace_log:
            from glom_tpu.obs.exporters import JsonlExporter

            trace_exporter = JsonlExporter(path=trace_log)
        self.tracer = Tracer(
            clock=self._clock, sink=TraceSink(max_traces=trace_max_traces),
            registry=self.registry, exporter=trace_exporter,
        )

        # fleet capacity plane: per-replica signal series ingested from
        # the /healthz capacity summaries the health loop ALREADY fetches
        # (zero extra HTTP), rolled up into fleet aggregates the dry-run
        # advisor judges.  Recommendation changes land on the timeline.
        from glom_tpu.obs.capacity import DEFAULT_POLICY, FleetCapacityPlane

        self.capacity = FleetCapacityPlane(
            policy=capacity_policy or DEFAULT_POLICY,
            persist_windows=capacity_persist_windows,
            clock=self._clock,
            registry=self.registry,
            on_recommend=lambda rec: self.note_event(
                "capacity_recommendation", action=rec["action"],
                reasons=rec.get("reasons", []),
                persisted=rec.get("persisted", 0)),
        )

        # fleet quality plane: replica sketch summaries ride the SAME
        # /healthz fetch; merge is exact (fixed-discretization sketches,
        # associative bin-wise adds), so fleet p95s are true percentiles
        # over every replica's observations, not averages of averages
        from glom_tpu.obs.quality import FleetQualityPlane

        self.quality = FleetQualityPlane(
            store=self.capacity.store, registry=self.registry,
            clock=self._clock,
        )

        # -- fleet bulk-job sharding (glom_tpu.serving.bulk) ---------------
        # the router owns the slot-range partition: submit cuts
        # [0, total) across healthy replicas, the health loop witnesses
        # per-shard durable cursors riding /healthz["bulk"], and a dead
        # owner's remaining ranges are re-cut onto survivors from the
        # last witnessed cursor (stale is safe: re-execution into the
        # range-keyed sink is idempotent — docs/BULK.md)
        self._jobs: Dict[str, dict] = {}
        self._jobs_lock = threading.Lock()

        # consistent-hash ring over ALL replicas (ejection skips forward at
        # lookup time, so only the dead replica's keys move)
        self._ring: List[Tuple[int, Replica]] = sorted(
            (int(hashlib.sha1(f"{r.name}#{v}".encode()).hexdigest()[:16], 16),
             r)
            for r in self.replicas for v in range(_VNODES)
        )
        self._ring_keys = [h for h, _ in self._ring]
        self._gauge_replicas()

    # -- event timeline -----------------------------------------------------
    def note_event(self, event: str, **fields) -> None:
        """Append one fleet state transition to the bounded timeline
        (``/debug/timeline``) as a typed TimelineEvent.  Leaf operation:
        takes only the timeline's own lock, callable from anywhere
        including under the dispatch lock."""
        self._timeline.note(event, **fields)

    def timeline(self) -> List[dict]:
        return self._timeline.events()

    # -- metrics helpers ----------------------------------------------------
    def _gauge_replicas(self) -> None:
        healthy = sum(r.healthy for r in self.replicas)
        self.registry.gauge(
            "router_replicas_total", help="replicas configured",
        ).set(len(self.replicas))
        self.registry.gauge(
            "router_replicas_healthy", help="replicas in dispatch rotation",
        ).set(healthy)

    # -- health: probe loop, ejection, re-admission -------------------------
    def _probe(self, replica: Replica) -> Optional[dict]:
        try:
            status, _, body = self._http(
                "GET", f"{replica.url}/healthz", None, {},
                self.health_timeout_s,
            )
            if status != 200:
                return None
            health = json.loads(body)
            return health if health.get("status") == "ok" else None
        except Exception:  # glomlint: disable=conc-broad-except -- any probe failure (refused, timeout, bad JSON, injected test fault) means unhealthy; the caller counts the streak and ejection makes it visible
            return None

    def _note_failure(self, replica: Replica) -> None:
        """One observed failure (probe or request path); ejects at the
        ``eject_after`` streak.  Caller holds the lock."""
        replica.fail_streak += 1
        if replica.healthy and replica.fail_streak >= self.eject_after:
            replica.healthy = False
            replica.ejections += 1
            self.registry.counter(
                "router_ejections_total",
                help="replicas removed from dispatch after failures",
            ).inc()
            self._gauge_replicas()
            self.note_event("ejection", replica=replica.name,
                            fail_streak=replica.fail_streak)
        # backoff: probes of a persistently-dead replica stretch out
        # (doubling per failure past ejection, capped), so a downed box
        # costs one cheap probe per backoff window, not per interval
        over = max(0, replica.fail_streak - self.eject_after)
        factor = min(2 ** over, self.probe_backoff_max)
        replica.next_probe_at = self._clock() + self.health_interval_s * factor

    def _catch_up(self, replica: Replica) -> bool:
        """A re-admission candidate that missed a coordinated rollout must
        reach the fleet step BEFORE taking traffic, or the fleet would mix
        versions.  Drives the same prepare/commit pair, singly."""
        try:
            status, _, body = self._http(
                "POST", f"{replica.url}/admin/reload/prepare",
                json.dumps({"step": self.fleet_step}).encode(),
                {"Content-Type": "application/json"}, self.admin_timeout_s,
            )
            if status != 200:
                return False
            staged = json.loads(body)
            if (staged.get("staged_step") is None
                    and staged.get("serving_step") != self.fleet_step):
                return False
            status, _, body = self._http(
                "POST", f"{replica.url}/admin/reload/commit", b"", {},
                self.admin_timeout_s,
            )
            if status != 200 or json.loads(body).get(
                    "step") != self.fleet_step:
                return False
            # free the displaced tree — this replica's catch-up is not a
            # rollout anyone will roll back
            self._admin(replica, "finalize", timeout=self.commit_timeout_s)
            return True
        except Exception:  # glomlint: disable=conc-broad-except -- a failed catch-up keeps the replica ejected (False); the next health pass retries and the fail streak stays observable
            return False

    def check_health_once(self, *, force: bool = False) -> None:
        """One pass over every replica whose probe is due (``force`` probes
        all).  The health loop calls this each interval; tests call it
        directly against an injected clock."""
        now = self._clock()
        for replica in self.replicas:
            with self._lock:
                due = force or now >= replica.next_probe_at
            if not due:
                continue
            health = self._probe(replica)
            if health is None:
                with self._lock:
                    self._note_failure(replica)
                continue
            # fold the replica's capacity summary into the fleet series
            # BEFORE any dispatch-lock work: ingest takes only the
            # capacity plane's own lock, and a held-out replica's signal
            # is still a live probe worth recording
            self.capacity.ingest(replica.name, health.get("capacity"),
                                 t=now)
            self.quality.ingest(replica.name, health.get("quality"),
                                t=now)
            self._ingest_bulk(replica.name, health.get("bulk"))
            with self._lock:
                was_down = not replica.healthy
                if not was_down:
                    replica.last_health = health
                    replica.step = health.get("step")
                    self._admit(replica, False)
                    continue
            # -- re-admission: serialized with rollouts.  A replica
            # re-admitted mid-rollout would be invisible to the commit
            # (the rollout snapshotted the fleet before it came back)
            # AND pass the catch-up check against the STALE fleet_step —
            # then serve the old version after everyone else flipped.
            # Holding the replica out one more probe round is cheap;
            # mixing versions is not.
            if not self._rollout_lock.acquire(blocking=False):
                with self._lock:
                    replica.next_probe_at = (
                        self._clock() + self.health_interval_s)
                continue
            try:
                with self._lock:
                    replica.last_health = health
                    replica.step = health.get("step")
                    needs_catch_up = (
                        self.fleet_step is not None
                        and replica.step != self.fleet_step)
                    if not needs_catch_up:
                        self._admit(replica, True)
                        continue
                # catch-up runs OUTSIDE the dispatch lock (two HTTP
                # calls) but INSIDE the rollout lock: no rollout can
                # change fleet_step mid-catch-up
                if self._catch_up(replica):
                    with self._lock:
                        replica.step = self.fleet_step
                        self._admit(replica, True)
                else:
                    with self._lock:
                        self._note_failure(replica)
            finally:
                self._rollout_lock.release()
        # one advisor window per health pass: aggregate the freshest
        # per-replica signals and (maybe) emit a recommendation
        self.capacity.evaluate(now)
        # fleet quality rollup rides the same cadence: exact sketch merge
        # across replicas, fleet-aggregate series into the shared store
        self.quality.rollup(now)
        # bulk-job re-partition rides the health pass too: it needs the
        # ejection verdicts this pass just rendered, and it POSTs
        # submits, so it must run outside the dispatch lock
        self._repartition_jobs()

    def _admit(self, replica: Replica, was_down: bool) -> None:
        """Caller holds the lock."""
        replica.fail_streak = 0
        replica.next_probe_at = self._clock() + self.health_interval_s
        if was_down:
            replica.healthy = True
            self.registry.counter(
                "router_readmissions_total",
                help="ejected replicas restored to dispatch",
            ).inc()
            self._gauge_replicas()
            self.note_event("readmission", replica=replica.name,
                            step=replica.step)

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            self.check_health_once()

    # -- fleet bulk-job sharding (docs/BULK.md) -----------------------------
    def _jobs_post(self, replica: Replica, action: str, payload: dict
                   ) -> Tuple[int, dict]:
        try:
            status, _, raw = self._http(
                "POST", f"{replica.url}/admin/jobs/{action}",
                json.dumps(payload).encode(),
                {"Content-Type": "application/json"}, self.admin_timeout_s)
        except Exception:  # glomlint: disable=conc-broad-except -- a dead replica answers nothing; the caller records a failed assignment and the health loop's ejection + re-partition recover the range
            return 0, {}
        try:
            return status, json.loads(raw)
        except ValueError:
            return status, {}

    def _assign(self, name: str, base: dict, replica: Replica,
                lo: int, hi: int) -> bool:
        """Land one ``[lo, hi)`` shard of a job on a replica; records the
        ownership on success."""
        status, _ = self._jobs_post(
            replica, "submit",
            {**base, "shard": [lo, hi], "owner": replica.name})
        if status != 200:
            return False
        with self._jobs_lock:
            rec = self._jobs.get(name)
            if rec is not None:
                rec["owners"].setdefault(replica.name, []).append((lo, hi))
        return True

    def submit_job(self, payload: dict) -> dict:
        """Fleet submit: cut ``[0, total)`` across the healthy replicas
        (``partition_range`` — the ElasticBatches contiguity contract
        generalized) and land one shard per replica via its
        ``/admin/jobs/submit``.  Every replica writes into the SAME sink
        directory (shared filesystem), so the finished parts assemble
        into one output regardless of which replica ran which range."""
        from glom_tpu.bulk.jobs import partition_range

        name = payload.get("name")
        if not name:
            raise ValueError("fleet submit needs a job name")
        total = payload.get("total")
        if total is None:
            m = re.match(r"^synthetic:([1-9]\d*)$",
                         str(payload.get("dataset", "")))
            if m is None:
                raise ValueError(
                    "fleet submit needs an explicit total (a file-glob "
                    "dataset may list differently per host)")
            total = int(m.group(1))
        total = int(total)
        base = {k: v for k, v in payload.items()
                if k not in ("shard", "owner")}
        base["total"] = total
        with self._lock:
            healthy = [r for r in self.replicas if r.healthy]
        if not healthy:
            raise NoHealthyReplica("no healthy replica to take the job")
        with self._jobs_lock:
            if name in self._jobs:
                raise ValueError(f"job {name!r} already submitted to "
                                 f"the fleet")
            self._jobs[name] = {
                "payload": base, "total": total, "status": "running",
                "owners": {}, "witnessed": {}, "revoked": [],
            }
        failed = []
        for i, (lo, hi) in enumerate(partition_range(0, total,
                                                     len(healthy))):
            # first choice by position; a refusal (bulk disabled, dead
            # mid-submit) falls through the rest of the rotation
            order = healthy[i % len(healthy):] + healthy[:i % len(healthy)]
            if not any(self._assign(name, base, r, lo, hi)
                       for r in order):
                failed.append((lo, hi))
        with self._jobs_lock:
            rec = self._jobs[name]
            owners = {o: [list(r) for r in rs]
                      for o, rs in rec["owners"].items()}
            if failed and not rec["owners"]:
                del self._jobs[name]  # nobody took anything: clean slate
        if failed:
            raise RuntimeError(
                f"job {name!r}: no replica accepted ranges {failed}")
        self.note_event("bulk_submit", job=name, total=total,
                        owners=owners)
        return self.job_status(name)

    def job_status(self, name: Optional[str] = None) -> dict:
        """One job's fleet progress (``name``), else every job plus the
        aggregate backlog — built from the cursors the health loop
        witnessed, so it costs no extra HTTP."""
        with self._jobs_lock:
            names = [name] if name is not None else sorted(self._jobs)
            jobs = {}
            backlog = 0
            for n in names:
                rec = self._jobs.get(n)
                if rec is None:
                    raise KeyError(f"no fleet job {n!r}")
                shards = []
                done = 0
                for owner, w in sorted(rec["witnessed"].items()):
                    for s in sorted(w.get("shards", {}).values(),
                                    key=lambda s: s["lo"]):
                        shards.append({**s, "owner": owner})
                        done += s["cursor"] - s["lo"]
                total = rec["total"]
                done = min(done, total)
                if rec["status"] not in ("cancelled", "paused") \
                        and done >= total:
                    rec["status"] = "done"
                jobs[n] = {
                    "name": n, "status": rec["status"], "total": total,
                    "done": done, "remaining": total - done,
                    "owners": {o: [list(r) for r in rs]
                               for o, rs in rec["owners"].items()},
                    "shards": shards,
                }
                if rec["status"] in ("running", "paused"):
                    backlog += total - done
        if name is not None:
            return jobs[name]
        return {"jobs": jobs, "backlog": backlog}

    def job_admin(self, action: str, name: str) -> dict:
        """Fan a pause/resume/cancel out to every owning replica."""
        if action not in ("pause", "resume", "cancel"):
            raise ValueError(f"no fleet jobs action {action!r}")
        with self._jobs_lock:
            rec = self._jobs.get(name)
            if rec is None:
                raise KeyError(f"no fleet job {name!r}")
            owner_names = sorted(rec["owners"])
        with self._lock:
            targets = [r for r in self.replicas if r.name in owner_names]
        acks = {}
        for replica in targets:
            status, _ = self._jobs_post(replica, action, {"name": name})
            acks[replica.name] = status == 200
        with self._jobs_lock:
            rec = self._jobs.get(name)
            if rec is not None:
                rec["status"] = {"pause": "paused", "resume": "running",
                                 "cancel": "cancelled"}[action]
        self.note_event(f"bulk_{action}", job=name, acks=acks)
        return {"action": action, "acks": acks, **self.job_status(name)}

    def _ingest_bulk(self, replica_name: str,
                     bulk: Optional[dict]) -> None:
        """Fold a replica's ``/healthz`` bulk summary into the fleet job
        registry.  The per-shard durable cursors witnessed here are the
        resume points a re-partition cuts from when the replica dies —
        at worst one health interval stale, which only means a survivor
        re-executes a little of what the dead replica finished (the
        range-keyed sink makes that rewrite byte-identical)."""
        if not bulk:
            return
        with self._jobs_lock:
            for jname, jst in (bulk.get("jobs") or {}).items():
                rec = self._jobs.get(jname)
                if rec is None:
                    continue  # locally-submitted job, not fleet-managed
                w = rec["witnessed"].setdefault(
                    replica_name, {"status": None, "shards": {}})
                w["status"] = jst.get("status")
                for s in jst.get("shards", ()):
                    w["shards"][str(s["lo"])] = {
                        "lo": int(s["lo"]), "hi": int(s["hi"]),
                        "cursor": int(s["cursor"]),
                    }

    def _repartition_jobs(self) -> None:
        """Re-cut every dead owner's unfinished ranges onto healthy
        survivors, each resuming from its last WITNESSED durable cursor.
        Also revokes (cancels) the job on any moved-away owner that came
        back, so a re-admitted replica doesn't duplicate work a survivor
        now owns.  Runs in the health pass, outside the dispatch lock —
        it POSTs submits."""
        with self._lock:
            healthy = [r for r in self.replicas if r.healthy]
            healthy_names = {r.name for r in healthy}
        if not healthy:
            return  # nobody to move work to; retry next pass
        moves, revokes = [], []
        with self._jobs_lock:
            for jname, rec in self._jobs.items():
                if rec["status"] in ("cancelled", "done"):
                    continue
                for owner in [o for o in rec["owners"]
                              if o not in healthy_names]:
                    w = rec["witnessed"].get(owner, {}).get("shards", {})
                    remaining = []
                    for lo, hi in rec["owners"].pop(owner):
                        cur = int(w.get(str(lo), {}).get("cursor", lo))
                        if cur < hi:
                            remaining.append((cur, hi))
                    if owner not in rec["revoked"]:
                        rec["revoked"].append(owner)
                    if remaining:
                        moves.append((jname, dict(rec["payload"]),
                                      owner, remaining))
                for owner in rec["revoked"]:
                    if owner in healthy_names:
                        revokes.append((jname, owner))
        from glom_tpu.bulk.jobs import partition_range

        for jname, base, dead, remaining in moves:
            blocks = []
            for c, hi in remaining:
                blocks.extend(partition_range(c, hi, len(healthy)))
            unassigned = []
            for i, (lo, hi) in enumerate(blocks):
                order = (healthy[i % len(healthy):]
                         + healthy[:i % len(healthy)])
                if not any(self._assign(jname, base, r, lo, hi)
                           for r in order):
                    unassigned.append((lo, hi))
            if unassigned:
                # nobody took these now: park them back on the dead
                # owner so the next health pass retries the re-partition
                with self._jobs_lock:
                    rec = self._jobs.get(jname)
                    if rec is not None:
                        rec["owners"].setdefault(
                            dead, []).extend(unassigned)
                        if dead in rec["revoked"]:
                            rec["revoked"].remove(dead)
            self.note_event(
                "bulk_repartition", job=jname, dead=dead,
                moved=[list(b) for b in blocks if b not in unassigned],
                survivors=sorted(r.name for r in healthy))
        for jname, owner in revokes:
            with self._lock:
                replica = next((r for r in self.replicas
                                if r.name == owner), None)
            if replica is None:
                continue
            status, _ = self._jobs_post(replica, "cancel", {"name": jname})
            if status in (200, 404):
                with self._jobs_lock:
                    rec = self._jobs.get(jname)
                    if rec is not None and owner in rec["revoked"]:
                        rec["revoked"].remove(owner)
                self.note_event("bulk_revoke", job=jname, replica=owner)

    # -- dispatch -----------------------------------------------------------
    def _hash_pick(self, key: str) -> Optional[Replica]:
        """Consistent-hash lookup: first HEALTHY replica clockwise from the
        key's point.  Caller holds the lock."""
        h = int(hashlib.sha1(key.encode()).hexdigest()[:16], 16)
        start = bisect.bisect_left(self._ring_keys, h)
        for i in range(len(self._ring)):
            _, replica = self._ring[(start + i) % len(self._ring)]
            if replica.healthy:
                return replica
        return None

    def pick(self, affinity_key: Optional[str] = None,
             exclude: Sequence[Replica] = ()) -> Replica:
        """Choose a replica: consistent-hash with an affinity key,
        least-loaded (ties rotated) otherwise.  ``exclude`` holds replicas
        already tried this request (failover never retries the same box).

        The commit gate is checked INSIDE the lock that also increments
        ``inflight``: the rollout closes the gate under the same lock, so
        after ``coordinated_reload`` clears it, every request is either
        already counted in-flight (the drain sees it) or will re-wait —
        no request can slip between a gate check and its accounting and
        land on a half-committed fleet."""
        while True:
            if not self._dispatch_open.wait(timeout=self.gate_timeout_s):
                self.registry.counter(
                    "router_no_replica_total",
                    help="requests that found no healthy replica",
                ).inc()
                raise NoHealthyReplica(
                    "dispatch gated longer than gate_timeout_s")
            with self._lock:
                if not self._dispatch_open.is_set():
                    continue  # gate closed between wait and lock: re-wait
                return self._pick_locked(affinity_key, exclude)

    def _pick_locked(self, affinity_key, exclude) -> Replica:
        """Caller holds the lock and has passed the gate."""
        if affinity_key:
            replica = self._hash_pick(affinity_key)
            if replica is not None and replica not in exclude:
                replica.inflight += 1
                return replica
            # the hashed replica was just tried (or everything on the
            # ring is down): fail over to least-loaded
        candidates = [r for r in self.replicas
                      if r.healthy and r not in exclude]
        if not candidates:
            self.registry.counter(
                "router_no_replica_total",
                help="requests that found no healthy replica",
            ).inc()
            raise NoHealthyReplica(
                f"0 of {len(self.replicas)} replicas available"
            )
        least = min(r.inflight for r in candidates)
        tied = [r for r in candidates if r.inflight == least]
        replica = tied[self._rr % len(tied)]
        self._rr += 1
        replica.inflight += 1
        return replica

    def dispatch(self, endpoint: str, body: bytes, headers: Dict[str, str],
                 root_span=None, affinity_key: Optional[str] = None,
                 ) -> Tuple[int, Dict[str, str], bytes, Replica]:
        """Proxy one request: pick (which gates — a commit in progress
        holds new arrivals), forward; connection-level failure fails over
        to the next healthy replica.  Returns ``(status, headers, body,
        replica)``; raises :class:`NoHealthyReplica` when the fleet is
        dry."""
        tracer = self.tracer
        t_route0 = tracer.clock()
        tried: List[Replica] = []
        last_exc: Optional[Exception] = None
        while len(tried) < len(self.replicas):
            replica = self.pick(affinity_key, exclude=tried)
            if root_span is not None and not tried:
                tracer.record(
                    SPAN_ROUTE, root_span, t_route0, tracer.clock(),
                    attrs={"replica": replica.name,
                           "policy": "hash" if affinity_key else
                           "least_loaded"},
                )
            tried.append(replica)
            proxy_span = None
            fwd = dict(headers)
            if root_span is not None:
                proxy_span = tracer.start_span(
                    SPAN_PROXY, root_span,
                    attrs={"replica": replica.name, "endpoint": endpoint},
                )
                # the engine's request span will parent under THIS
                # attempt's proxy span — retries re-parent cleanly
                if _HEX_ID.fullmatch(root_span.trace_id):
                    fwd["traceparent"] = format_traceparent(
                        root_span.trace_id, proxy_span.span_id)
                elif "X-Request-Id" in fwd:
                    # non-hex operator id: the engine adopts the forwarded
                    # X-Request-Id as its trace id (it wins over the
                    # traceparent's trace field), so the header is purely
                    # the parent-span carrier — pad the span id into the
                    # trace field to keep the W3C shape valid
                    fwd["traceparent"] = format_traceparent(
                        proxy_span.span_id, proxy_span.span_id)
            try:
                status, resp_headers, resp_body = self._http(
                    "POST", f"{replica.url}/{endpoint}", body, fwd,
                    self.request_timeout_s,
                )
            except Exception as e:  # connection-level: fail over
                last_exc = e
                with self._lock:
                    replica.inflight -= 1
                    replica.errors += 1
                    self._note_failure(replica)
                if proxy_span is not None:
                    tracer.end(proxy_span, attrs={"error": repr(e)})
                self.registry.counter(
                    "router_failovers_total",
                    help="proxy attempts retried on another replica after "
                         "a connection failure",
                ).inc()
                continue
            with self._lock:
                replica.inflight -= 1
                replica.requests += 1
                replica.fail_streak = 0
                if status >= 500:
                    replica.errors += 1
            if proxy_span is not None:
                tracer.end(proxy_span, attrs={"status": status})
            return status, resp_headers, resp_body, replica
        raise NoHealthyReplica(
            f"all {len(tried)} replicas failed: {last_exc!r}")

    def similar_fanout(self, body: bytes, headers: Dict[str, str],
                       root_span=None) -> Tuple[int, dict, str]:
        """POST /similar to EVERY healthy replica and merge the answers.

        Unlike ``dispatch`` (one replica serves the request), a similarity
        query must see the whole index: replicas may each hold a different
        shard family (a fleet bulk job shards the slot range, so replica A
        indexed slots [0,N) while B indexed [N,2N)).  The merge is
        deterministic regardless of reply order: per image, candidates
        from all replicas are deduped by slot keeping the best score
        (shared-index deployments answer identically everywhere, so
        duplicates are exact), then sorted by ``(-score, slot)`` and cut
        to k.  Replicas without an index (404) just don't contribute.

        Returns ``(status, payload_dict, served_by)``; raises
        :class:`NoHealthyReplica` when nothing answered at all.
        """
        tracer = self.tracer
        with self._lock:
            fleet = [r for r in self.replicas if r.healthy]
        if not fleet:
            raise NoHealthyReplica("no healthy replicas for /similar")
        merged: Optional[List[Dict[int, float]]] = None
        level = k = None
        shard_stats: Dict[str, dict] = {}
        answered: List[str] = []
        last_err: Optional[Tuple[int, dict]] = None
        last_exc: Optional[Exception] = None
        for replica in fleet:
            proxy_span = None
            if root_span is not None:
                proxy_span = tracer.start_span(
                    SPAN_PROXY, root_span,
                    attrs={"replica": replica.name, "endpoint": "similar"})
            try:
                status, _, resp_body = self._http(
                    "POST", f"{replica.url}/similar", body, dict(headers),
                    self.request_timeout_s)
            except Exception as e:  # connection-level: skip this shard
                last_exc = e
                with self._lock:
                    replica.errors += 1
                    self._note_failure(replica)
                if proxy_span is not None:
                    tracer.end(proxy_span, attrs={"error": repr(e)})
                continue
            with self._lock:
                replica.requests += 1
                replica.fail_streak = 0
                if status >= 500:
                    replica.errors += 1
            if proxy_span is not None:
                tracer.end(proxy_span, attrs={"status": status})
            if status != 200:
                # 404 = no index on that replica (fine: it holds no
                # shard).  Anything else is remembered so an all-error
                # fan-out surfaces a real diagnosis, not a bare 503.
                if status != 404:
                    try:
                        last_err = (status, json.loads(resp_body))
                    except ValueError:
                        last_err = (status, {"error": resp_body.decode(
                            "utf-8", "replace")})
                continue
            try:
                payload = json.loads(resp_body)
                results = payload["results"]
            except (ValueError, KeyError, TypeError):
                last_err = (502, {"error": f"unparseable /similar reply "
                                           f"from {replica.name}"})
                continue
            answered.append(replica.name)
            if payload.get("index") is not None:
                shard_stats[replica.name] = payload["index"]
            if level is None:
                level, k = payload.get("level"), payload.get("k")
            if merged is None:
                merged = [dict() for _ in results]
            for best, hits in zip(merged, results):
                for hit in hits:
                    slot = int(hit["slot"])
                    score = float(hit["score"])
                    if slot not in best or score > best[slot]:
                        best[slot] = score
        if merged is None:
            if last_err is not None:
                return last_err[0], last_err[1], ""
            raise NoHealthyReplica(
                f"no replica answered /similar: {last_exc!r}")
        want = int(k) if k else 5
        results = []
        for best in merged:
            ranked = sorted(best.items(), key=lambda kv: (-kv[1], kv[0]))
            results.append([{"slot": slot, "score": score}
                            for slot, score in ranked[:want]])
        self.registry.counter(
            "router_similar_fanouts_total",
            help="similarity queries fanned across the fleet's shards",
        ).inc()
        return 200, {"results": results, "level": level, "k": k,
                     "replicas": answered, "shards": shard_stats}, \
            ",".join(answered)

    # -- coordinated rollout ------------------------------------------------
    def _admin(self, replica: Replica, action: str,
               payload: Optional[dict] = None,
               timeout: Optional[float] = None) -> Optional[dict]:
        try:
            status, _, body = self._http(
                "POST", f"{replica.url}/admin/reload/{action}",
                json.dumps(payload).encode() if payload is not None else b"",
                {"Content-Type": "application/json"} if payload is not None
                else {},
                timeout if timeout is not None else self.admin_timeout_s,
            )
            return json.loads(body) if status == 200 else None
        except Exception:  # glomlint: disable=conc-broad-except -- admin helper contract: None for any failure; each rollout phase decides (abort/rollback/eject) and counts its own outcome
            return None

    def coordinated_reload(self, step: Optional[int] = None) -> dict:
        """Roll the whole healthy fleet to one checkpoint step; see module
        docstring for the two-phase protocol.  Returns a report dict with
        ``status`` in {"noop", "no_replicas", "aborted", "committed",
        "rolled_back"}.  The rollout's state-machine position is published
        as ``rollout_phase`` (healthz/console) and each outcome lands on
        the event timeline."""
        with self._rollout_lock:
            self.rollout_phase = "prepare"
            try:
                report = self._coordinated_reload_locked(step)
            finally:
                self.rollout_phase = "idle"
        if report["status"] != "noop":
            self.note_event(
                "rollout_" + report["status"],
                **{k: v for k, v in report.items()
                   if k in ("step", "replica", "phase", "detail",
                            "replicas")})
        return report

    def _coordinated_reload_locked(self, step: Optional[int] = None) -> dict:
        with self._lock:
            fleet = [r for r in self.replicas if r.healthy]
        if not fleet:
            return {"status": "no_replicas"}

        # -- phase 1: stage the SAME step everywhere ------------------
        # With no pinned step, DISCOVER the target first: walk the
        # fleet until some replica stages something newer than what it
        # serves.  One replica saying "nothing newer" is NOT a fleet
        # noop — a replica started earlier may serve an older step,
        # and the rollout is also the convergence mechanism for a
        # mixed fleet: if nobody stages but serving steps disagree,
        # the newest serving step becomes the target.
        target = step
        # the CONSERVATIVE pre-rollout version: the MINIMUM serving
        # step seen in phase 1.  It is only used to pin fleet_step on
        # a rolled-back rollout (so a suspect replica's re-admission
        # catch-up can never be steered to the new target) — on a
        # mixed fleet the first response's step could BE the target,
        # which would defeat the pin entirely.
        old_step: Optional[int] = None

        def note_serving(resp) -> None:
            nonlocal old_step
            s = resp.get("serving_step")
            if s is not None and (old_step is None or s < old_step):
                old_step = int(s)

        prepared: List[Replica] = []
        trivial: List[Replica] = []  # already serving the target
        # the replica whose prepare response is mid-validation: its
        # engine may have staged server-side before our validation
        # raised, so the except below must abort it alongside `prepared`
        inflight_prep: Optional[Replica] = None
        try:
            if target is None:
                serving: Dict[str, Optional[int]] = {}
                for replica in fleet:
                    inflight_prep = replica
                    # a prepare that stages nothing leaves nothing to
                    # settle; every path that lands a replica in
                    # `prepared` commits, rolls back, or aborts it below
                    resp = self._admin(replica, "prepare", {})  # glomlint: disable=proto-paired-call -- the noop return (nothing staged fleet-wide) has nothing to settle; the except below aborts every other early exit
                    if resp is None:
                        # the failed replica gets an abort too: a router-
                        # side timeout with engine-side success would
                        # strand a full staged param tree there
                        self._abort(prepared + [replica])
                        return {"status": "aborted", "phase": "prepare",
                                "replica": replica.name,
                                "detail": "prepare failed"}
                    note_serving(resp)
                    serving[replica.name] = resp.get("serving_step")
                    staged = resp.get("staged_step")
                    if staged is not None:
                        target = int(staged)
                        prepared.append(replica)
                        break  # pin the rest to this step below
                if target is None:
                    distinct = {v for v in serving.values()}
                    if len(distinct) <= 1:
                        return {"status": "noop",
                                "step": next(iter(distinct), None)}
                    target = max(v for v in distinct if v is not None)

            for replica in fleet:
                if replica in prepared:
                    continue
                inflight_prep = replica
                resp = self._admin(replica, "prepare", {"step": target})  # glomlint: disable=proto-paired-call -- the noop return below is only reachable with `prepared` empty; every other early exit aborts (loop bodies + the except below)
                if resp is None:
                    self._abort(prepared + [replica])
                    return {"status": "aborted", "phase": "prepare",
                            "replica": replica.name,
                            "detail": "prepare failed"}
                note_serving(resp)
                staged = resp.get("staged_step")
                if staged is None:
                    if resp.get("serving_step") == target:
                        trivial.append(replica)
                        continue
                    self._abort(prepared + [replica])
                    return {"status": "aborted", "phase": "prepare",
                            "replica": replica.name,
                            "detail": f"could not stage step {target}"}
                if int(staged) != target:
                    self._abort(prepared + [replica])
                    return {"status": "aborted", "phase": "prepare",
                            "replica": replica.name,
                            "detail": f"staged {staged} != target {target}"}
                prepared.append(replica)
        except Exception:
            # an unexpected failure mid-prepare (a malformed replica
            # response feeding int(), a raising transport) must not
            # strand staged param trees — neither on the replicas
            # already prepared NOR on the one whose response we were
            # validating (its engine may have staged before the
            # validation raised; an abort with nothing staged is a
            # no-op engine-side)
            extra = ([inflight_prep] if inflight_prep is not None
                     and inflight_prep not in prepared else [])
            self._abort(prepared + extra)
            raise
        if not prepared and not trivial:
            return {"status": "noop", "step": target}

        # -- phase 2: gate dispatch, drain, commit everywhere ---------
        # the gate closes UNDER the dispatch lock: _pick_locked checks
        # it in the same critical section that increments inflight, so
        # once clear() returns, every admitted request is visible to
        # the drain below and every unadmitted one re-waits
        with self._lock:
            self._dispatch_open.clear()
        self.rollout_phase = "drain"
        try:
            # drain in-flight work before the first commit: a response
            # computed DURING the commit window would expose a half-
            # committed fleet — or, worse, a dirty read of the new
            # step that a later rollback retracts.  With the gate
            # closed and in-flight at zero, every response completes
            # strictly before (all-old) or strictly after (all-new,
            # or all-old on rollback) the swap.
            drain_deadline = self._clock() + self.drain_timeout_s
            while True:
                with self._lock:
                    if all(r.inflight == 0 for r in self.replicas):
                        break
                if self._clock() >= drain_deadline:
                    # proceeding with stragglers in flight weakens the
                    # ordering guarantee for exactly those requests —
                    # never silently: the counter + warning make an
                    # undersized drain_timeout_s visible
                    self.registry.counter(
                        "router_drain_timeouts_total",
                        help="rollouts that committed with requests "
                             "still in flight (drain deadline hit)",
                    ).inc()
                    warnings.warn(
                        f"rollout drain did not reach zero in-flight "
                        f"within {self.drain_timeout_s}s; committing "
                        f"anyway — in-flight responses may interleave "
                        f"with the version flip", stacklevel=2,
                    )
                    self.note_event("drain_timeout")
                    break
                self._sleep(0.005)
            self.rollout_phase = "commit"
            committed: List[Replica] = []
            for replica in prepared:
                resp = self._admin(replica, "commit",
                                   timeout=self.commit_timeout_s)
                if resp is None or resp.get("step") != target:
                    # roll the fleet back BEFORE the gate reopens: no
                    # post-gate dispatch may ever see the new step.
                    # The failed replica gets an abort too — an HTTP-
                    # level commit failure may have left it staged.
                    for done in committed:
                        if self._admin(done, "rollback",
                                       timeout=self.commit_timeout_s
                                       ) is None:
                            # the rollback itself failed: this replica
                            # may still serve the NEW step in a fleet
                            # that reverted — eject it; re-admission
                            # catch-up (fleet_step pinned below) rolls
                            # it back before it takes traffic again
                            with self._lock:
                                done.fail_streak = max(
                                    done.fail_streak,
                                    self.eject_after - 1)
                                self._note_failure(done)
                            self.registry.counter(
                                "router_rollback_failures_total",
                                help="replicas whose rollback call "
                                     "failed (ejected until catch-up)",
                            ).inc()
                    self._abort([r for r in prepared
                                 if r not in committed])
                    # the failed replica may have committed server-side
                    # with the response lost: eject it, and pin the
                    # fleet step to the OLD version so re-admission
                    # catch-up forces it back into agreement before it
                    # takes traffic again
                    with self._lock:
                        replica.fail_streak = max(
                            replica.fail_streak, self.eject_after - 1)
                        self._note_failure(replica)
                    if old_step is not None:
                        self.fleet_step = int(old_step)
                    self.registry.counter(
                        "router_rollbacks_total",
                        help="coordinated rollouts reverted after a "
                             "commit failure",
                    ).inc()
                    return {"status": "rolled_back",
                            "replica": replica.name,
                            "step": target,
                            "detail": "commit failed; fleet reverted"}
                committed.append(replica)
            self.fleet_step = target
            with self._lock:
                for replica in prepared + trivial:
                    replica.step = target
            self.registry.counter(
                "router_rollouts_total",
                help="coordinated fleet reloads committed",
            ).inc()
            self.registry.gauge(
                "router_fleet_step",
                help="checkpoint step the fleet serves",
            ).set(target)
        finally:
            self._dispatch_open.set()
        # the rollout landed everywhere: release each replica's
        # rollback point (a full second device param tree) AFTER the
        # gate reopened — memory hygiene must not extend the gated
        # window, and the rollback window is over by definition here.
        # A failed finalize only delays the release to the next
        # rollout; never worth failing the rollout over.
        for replica in prepared:
            self._admin(replica, "finalize",
                        timeout=self.commit_timeout_s)
        return {"status": "committed", "step": target,
                "replicas": [r.name for r in prepared + trivial]}

    def _abort(self, prepared: Sequence[Replica]) -> None:
        for replica in prepared:
            self._admin(replica, "abort")

    def _rollout_loop(self) -> None:
        while not self._stop.wait(self.rollout_poll_s):
            try:
                self.coordinated_reload()
            except Exception as e:  # the poll loop must outlive any rollout bug
                self.registry.counter(
                    "router_rollout_errors_total",
                    help="rollout poll iterations that raised",
                ).inc()
                warnings.warn(
                    f"rollout poll iteration raised "
                    f"({type(e).__name__}: {e}); router continues",
                    stacklevel=2,
                )

    # -- aggregate views ----------------------------------------------------
    def health(self) -> dict:
        with self._lock:
            replicas = [r.to_dict() for r in self.replicas]
            healthy = [r for r in self.replicas if r.healthy]
            model = next(
                (r.last_health for r in healthy if r.last_health), None)
        n = len(healthy)
        status = "ok" if n == len(self.replicas) else (
            "degraded" if n else "down")
        out = {
            "status": status,
            "role": "router",
            "healthy_replicas": n,
            "fleet_step": self.fleet_step,
            # glomlint: disable=conc-unguarded-attr -- live phase indicator: /healthz must answer while a rollout holds _rollout_lock for its whole prepare/drain/commit cycle; a stale phase string is the display contract
            "rollout_phase": self.rollout_phase,
            "replicas": replicas,
        }
        with self._jobs_lock:
            if self._jobs:
                out["bulk_jobs"] = {
                    n: rec["status"] for n, rec in self._jobs.items()}
        if model:
            # surface the model's input contract so loadgen (and any other
            # client) reads the router exactly like a single engine
            for key in ("image_size", "patch_size", "channels", "levels",
                        "dim", "step", "buckets", "quant", "mesh",
                        "param_sharding", "hierarchy"):
                if key in model:
                    out[key] = model[key]
        return out

    def metrics_text(self, *, openmetrics: bool = False) -> str:
        """Router families verbatim + every reachable replica's families
        relabeled with ``replica="<name>"`` (HELP/TYPE deduped across
        replicas — Prometheus rejects repeated metadata).  Replica
        scrapes run CONCURRENTLY: serial fetches would stack one
        ``health_timeout_s`` per blackholed replica and blow a typical
        Prometheus scrape_timeout exactly when replicas are unhealthy.
        ``openmetrics=True`` (the front negotiated it via Accept)
        forwards the negotiation to each replica scrape and renders the
        router's own exemplars; a plain 0.0.4 client gets (and causes the
        replicas to emit) exemplar-free text."""
        from concurrent.futures import ThreadPoolExecutor

        replicas = list(self.replicas)
        fetch_headers = ({"Accept": OPENMETRICS_CONTENT_TYPE}
                         if openmetrics else {})

        def fetch(replica):
            try:
                return self._http("GET", f"{replica.url}/metrics", None,
                                  fetch_headers, self.health_timeout_s)
            except Exception:  # glomlint: disable=conc-broad-except -- a dead replica's scrape is skipped from the aggregate; ejecting it is the health loop's job, not the scrape's
                return None

        with ThreadPoolExecutor(
            max_workers=min(8, max(1, len(replicas)))
        ) as pool:
            fetched = list(pool.map(fetch, replicas))

        parts = [prometheus_lines(self.registry, exemplars=openmetrics)]
        seen_meta = set()
        for replica, result in zip(replicas, fetched):
            if result is None:
                parts.append(f"# replica {replica.name} unreachable\n")
                continue
            status, _, body = result
            if status != 200:
                parts.append(f"# replica {replica.name} /metrics -> "
                             f"{status}\n")
                continue
            out = []
            for line in body.decode(errors="replace").splitlines():
                if line.startswith("#"):
                    # replica EOF terminators must not land mid-aggregate
                    if line.strip() != "# EOF" and line not in seen_meta:
                        seen_meta.add(line)
                        out.append(line)
                    continue
                m = _SAMPLE_RE.match(line)
                if not m:
                    continue
                name, labels, rest = m.groups()
                inner = labels[1:-1] if labels else ""
                label = f'replica="{replica.name}"' + (
                    f",{inner}" if inner else "")
                out.append(f"{name}{{{label}}}{rest}")
            parts.append("\n".join(out) + "\n")
        text = "".join(parts)
        if openmetrics:
            # strict OpenMetrics forbids interleaved metric families: the
            # per-replica blocks repeat family names (and the router now
            # shares serving-span families with its replicas), so the
            # aggregate is regrouped family-contiguous and terminated
            from glom_tpu.obs.exporters import regroup_families

            text = regroup_families(text) + "# EOF\n"
        return text

    # -- lifecycle ----------------------------------------------------------
    def start(self, *, health: bool = True) -> None:
        """Probe every replica once synchronously (a dead replica must be
        ejected before the first request, not an interval later), then run
        the probe loop — and the rollout poll when configured."""
        self.check_health_once(force=True)
        if health and self.health_interval_s > 0:
            t = threading.Thread(target=self._health_loop,
                                 name="glom-router-health", daemon=True)
            t.start()
            self._threads.append(t)
        if self.rollout_poll_s > 0:
            t = threading.Thread(target=self._rollout_loop,
                                 name="glom-router-rollout", daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        self._dispatch_open.set()  # release any gated handler threads
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        if self.tracer.exporter is not None:
            self.tracer.exporter.close()


# ---------------------------------------------------------------------------
# stdlib HTTP front
# ---------------------------------------------------------------------------
class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # stdlib default backlog is 5: a burst of fresh connections (clients
    # without keep-alive, a loadgen wave) overflows it and the dropped
    # SYNs retransmit on second-scale timers — a 300ms+ latency cliff
    # that looks like router overhead but is just the listen queue
    request_queue_size = 128

    def __init__(self, addr, handler, router: FleetRouter, *,
                 quiet: bool = True):
        super().__init__(addr, handler)
        self.router = router
        self.quiet = quiet


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "glom-router"
    protocol_version = "HTTP/1.1"
    # headers and body are separate writes; without TCP_NODELAY Nagle can
    # hold the body segment against a delayed ACK — 40ms quanta on a
    # reply that took 2ms to compute
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        if not getattr(self.server, "quiet", True):
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload, content_type="application/json",
               extra_headers: Optional[Dict[str, str]] = None) -> None:
        if code >= 400:
            self.server.router.registry.counter(
                f"router_errors_{code // 100}xx",
                help=f"router replies with a {code // 100}xx status",
            ).inc()
        body = (json.dumps(payload) if isinstance(payload, (dict, list))
                else payload)
        body = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
            tid = self._trace_root.trace_id
            if _HEX_ID.fullmatch(tid):
                self.send_header("traceparent", format_traceparent(
                    tid, self._trace_root.span_id))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self._request_id = None
        router = self.server.router
        from urllib.parse import urlparse

        parsed = urlparse(self.path)
        if parsed.path == "/healthz":
            self._reply(200, router.health())
        elif parsed.path == "/metrics":
            # see server.py: exemplars only under negotiated OpenMetrics
            om = wants_openmetrics(self.headers.get("Accept"))
            self._reply(200, router.metrics_text(openmetrics=om),
                        content_type=(OPENMETRICS_CONTENT_TYPE if om
                                      else PROM_TEXT_CONTENT_TYPE))
        # -- debug plane: the fleet observatory's pull endpoints -----------
        elif parsed.path == "/debug/traces":
            status, payload = debug_traces_payload(
                router.tracer, parsed.query, role="router")
            self._reply(status, payload)
        elif parsed.path == "/debug/timeline":
            self._reply(200, {
                "role": "router",
                "fleet_step": router.fleet_step,
                "rollout_phase": router.rollout_phase,
                "events": router.timeline(),
            })
        elif parsed.path == "/debug/series":
            # fleet TSDB-lite pull plane: per-replica (labeled) and
            # fleet-aggregate capacity series (glom_tpu.obs.timeseries)
            self._reply(200, router.capacity.series_payload(parsed.query))
        elif parsed.path == "/capacity":
            self._reply(200, router.capacity.payload())
        elif parsed.path == "/quality":
            # fleet quality rollup: exactly-merged replica sketches plus
            # the per-replica summaries they were merged from
            self._reply(200, router.quality.payload())
        elif parsed.path == "/admin/jobs/status":
            # fleet bulk-job progress: built from health-loop-witnessed
            # cursors, so the read costs no replica HTTP
            from urllib.parse import parse_qs

            q = parse_qs(parsed.query)
            try:
                self._reply(200, router.job_status(q.get("name",
                                                         [None])[0]))
            except KeyError as e:
                self._reply(404, {"error": str(e)})
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802
        self._request_id = None
        router = self.server.router
        if self.path == "/rollout":
            length = int(self.headers.get("Content-Length") or 0)
            payload = {}
            if length:
                try:
                    payload = json.loads(self.rfile.read(length))
                except ValueError as e:
                    self._reply(400, {"error": f"invalid JSON: {e}"})
                    return
            if not isinstance(payload, dict):
                self._reply(400, {"error": "body must be a JSON object"})
                return
            step = payload.get("step")
            report = router.coordinated_reload(
                step=int(step) if step is not None else None)
            code = 200 if report["status"] in ("committed", "noop") else 502
            self._reply(code, report)
            return
        if self.path.startswith("/admin/jobs/"):
            # fleet bulk-job admin: submit shards the range across the
            # healthy replicas; pause/resume/cancel fan out to owners
            action = self.path[len("/admin/jobs/"):]
            length = int(self.headers.get("Content-Length") or 0)
            payload = {}
            if length:
                try:
                    payload = json.loads(self.rfile.read(length))
                except ValueError as e:
                    self._reply(400, {"error": f"invalid JSON: {e}"})
                    return
            if not isinstance(payload, dict):
                self._reply(400, {"error": "body must be a JSON object"})
                return
            try:
                if action == "submit":
                    self._reply(200, router.submit_job(payload))
                elif action == "status":
                    self._reply(200,
                                router.job_status(payload.get("name")))
                elif action in ("pause", "resume", "cancel"):
                    name = payload.get("name")
                    if not name:
                        self._reply(400, {"error": f"{action} needs a "
                                                   f"job name"})
                        return
                    self._reply(200, router.job_admin(action, name))
                else:
                    self._reply(404,
                                {"error": f"no jobs action {action!r}"})
            except KeyError as e:
                self._reply(404, {"error": str(e)})
            except NoHealthyReplica as e:
                self._reply(503, {"error": str(e)})
            except (RuntimeError, ValueError) as e:
                self._reply(409, {"error": str(e)})
            return
        if self.path not in ROUTED_PATHS:
            self._reply(404, {"error": f"no route {self.path}"})
            return
        endpoint = self.path[1:]
        tracer = router.tracer

        rid_header = request_trace_id(self.headers.get("X-Request-Id"))
        remote = parse_traceparent(self.headers.get("traceparent"))
        root = tracer.start_trace(
            SPAN_ROUTER_REQUEST,
            trace_id=rid_header or (remote[0] if remote else None),
            parent_id=remote[1] if remote else None,
            attrs={"endpoint": endpoint},
        )
        self._trace_root = root
        self._request_id = rid_header or root.trace_id

        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            self._reply(400, {"error": f"bad Content-Length {length}"})
            tracer.end(root, attrs={"status": 400})
            return
        body = self.rfile.read(length)
        # tile the router handler exactly like the engine handler: parse
        # (headers + body read) and respond (reply write) recorded with
        # SHARED edges around the dispatch window, so the stitched trace's
        # coverage has no router-side instrumentation gap — the reply
        # write scales with the response body and was the uncovered tail
        # that dragged big-batch traces under the coverage bar
        t_read = tracer.clock()
        tracer.record(SPAN_PARSE, root, root.start, t_read)
        fwd = {"Content-Type": self.headers.get("Content-Type",
                                                "application/json")}
        if rid_header:
            fwd["X-Request-Id"] = rid_header
        affinity = self.headers.get("X-Affinity-Key")
        if affinity:
            fwd["X-Affinity-Key"] = affinity
        # the tenant identity must survive the hop or the engine-side
        # bulkheads (admission quota, per-tenant SLOs/metrics) are
        # silently inert in the router-fronted topology
        tenant = self.headers.get("X-Tenant")
        if tenant:
            fwd["X-Tenant"] = tenant
        if endpoint == "similar":
            # shard fan-out, not single-replica proxy: every healthy
            # replica answers from its index shards; merged top-k here
            try:
                status, payload, served = router.similar_fanout(
                    body, fwd, root_span=root)
            except NoHealthyReplica as e:
                self._reply(503, {"error": "no_replica", "detail": str(e)})
                tracer.end(root, attrs={"status": 503})
                return
            router.registry.counter(
                "router_requests_total",
                help="requests proxied to replicas",
            ).inc()
            t_done = tracer.clock()
            self._reply(status, payload,
                        extra_headers=({"X-Served-By": served}
                                       if served else None))
            t_end = tracer.clock()
            tracer.record(SPAN_RESPOND, root, t_done, t_end)
            tracer.end(root, attrs={"status": status}, at=t_end)
            return
        try:
            status, _resp_headers, resp_body, replica = router.dispatch(
                endpoint, body, fwd, root_span=root, affinity_key=affinity,
            )
        except NoHealthyReplica as e:
            self._reply(503, {"error": "no_replica", "detail": str(e)})
            tracer.end(root, attrs={"status": 503})
            return
        router.registry.counter(
            "router_requests_total", help="requests proxied to replicas",
        ).inc()
        t_done = tracer.clock()
        self._reply(status, resp_body,
                    extra_headers={"X-Served-By": replica.name})
        t_end = tracer.clock()
        tracer.record(SPAN_RESPOND, root, t_done, t_end)
        tracer.end(root, attrs={"status": status, "replica": replica.name},
                   at=t_end)


def make_router_server(router: FleetRouter, host: str = "127.0.0.1",
                       port: int = 0, *, quiet: bool = True
                       ) -> RouterHTTPServer:
    """Bind (port 0 = ephemeral); caller runs ``serve_forever``."""
    return RouterHTTPServer((host, port), _RouterHandler, router, quiet=quiet)


# ---------------------------------------------------------------------------
# CLI: route existing replicas, or --spawn an in-process fleet
# ---------------------------------------------------------------------------
def _spawn_fleet(n: int, args) -> Tuple[List[str], list]:
    """--spawn mode: N engines + servers in this process (CPU demo /
    single-host multi-replica; each replica owns its own batcher, cache,
    and params).  Returns (urls, [(engine, server), ...])."""
    from glom_tpu.serving.engine import ServingEngine, make_demo_checkpoint
    from glom_tpu.serving.server import make_server
    from glom_tpu import checkpoint as ckpt_lib

    if args.demo and ckpt_lib.latest_step(args.checkpoint_dir) is None:
        make_demo_checkpoint(args.checkpoint_dir)
    urls, members = [], []
    for i in range(n):
        engine = ServingEngine(
            args.checkpoint_dir,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            max_wait_ms=args.max_wait_ms,
            # replicas NEVER self-reload: the router's coordinated
            # rollout is the only param-swap path in a fleet
            reload_poll_s=0,
            quant=args.quant,
            # passed through raw: the engine normalizes None/'auto'/int
            warm_iters=args.warm_iters,
            # per-replica job store; the shared sink lives in the specs
            bulk_dir=(os.path.join(args.bulk_dir, f"r{i}")
                      if getattr(args, "bulk_dir", None) else None),
            # one shared index root is fine: the router's /similar merge
            # dedupes by slot, so full-copy and sharded layouts coexist
            index_dir=getattr(args, "index_dir", None),
            parse_thresholds=getattr(args, "parse_thresholds", None),
        )
        engine.start(watch=False)
        # per-replica capacity sampler: its /healthz summary feeds the
        # router's fleet capacity plane
        engine.capacity.start()
        server = make_server(engine, args.host, 0)
        threading.Thread(target=server.serve_forever, daemon=True,
                         name=f"glom-replica-{i}").start()
        host, port = server.server_address[:2]
        urls.append(f"http://{host}:{port}")
        members.append((engine, server))
    return urls, members


def main(argv=None) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(
        description="GLOM replica fleet router: least-loaded/consistent-"
                    "hash dispatch, health-aware ejection, coordinated "
                    "hot-reload",
    )
    p.add_argument("--replica", action="append", default=None, metavar="URL",
                   help="engine replica base URL (repeatable)")
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="spawn N in-process engine replicas from "
                        "--checkpoint-dir instead of routing external URLs")
    p.add_argument("--checkpoint-dir", default=None,
                   help="checkpoint dir for --spawn replicas")
    p.add_argument("--demo", action="store_true",
                   help="with --spawn: write a demo checkpoint if the dir "
                        "has none")
    p.add_argument("--buckets", default="1,2,4,8",
                   help="with --spawn: per-replica batch buckets")
    p.add_argument("--quant", default="f32", choices=["f32", "bf16", "int8"],
                   help="with --spawn: per-replica serving precision")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="with --spawn: per-replica micro-batch deadline")
    p.add_argument("--warm-iters", default=None, metavar="N|auto",
                   help="with --spawn: enable stateful sessions on every "
                        "replica (clients pin a session with "
                        "X-Affinity-Key: <session id>)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8800)
    p.add_argument("--health-interval-s", type=float, default=1.0,
                   help="replica /healthz probe period")
    p.add_argument("--eject-after", type=int, default=2,
                   help="consecutive failures before a replica is ejected")
    p.add_argument("--rollout-poll-s", type=float, default=0.0,
                   help="poll for new checkpoints and roll the fleet "
                        "forward every this many seconds; 0 = POST "
                        "/rollout only")
    p.add_argument("--trace-log", default=None,
                   help="JSONL file receiving one record per completed "
                        "router trace")
    p.add_argument("--capacity-policy", default=None, metavar="SPEC",
                   help="fleet dry-run autoscale policy, e.g. "
                        "'p95_ms<250,duty<0.8,shed<0.01' — evaluated over "
                        "fleet-aggregate series each health pass; emits "
                        "scale-up/down/rebalance RECOMMENDATIONS to the "
                        "timeline and GET /capacity, never acts")
    p.add_argument("--capacity-persist-windows", type=int, default=5,
                   help="consecutive scale-up windows before a replica-"
                        "side capacity_pressure incident is expected")
    p.add_argument("--bulk-dir", default=None, metavar="DIR",
                   help="--spawn mode: enable the bulk inference tier "
                        "with a per-replica job store under DIR/<name> "
                        "(docs/BULK.md); the router shards /admin/jobs/* "
                        "submits across the fleet")
    p.add_argument("--index-dir", default=None, metavar="DIR",
                   help="--spawn mode: similarity-index root handed to "
                        "every replica (POST /similar fans across the "
                        "fleet and merges top-k; docs/HIERARCHY.md)")
    p.add_argument("--parse-thresholds", default=None, metavar="T|T0,T1,..",
                   help="--spawn mode: per-level agreement thresholds for "
                        "POST /parse islanding (default 0.9)")
    p.add_argument("--platform", default="auto",
                   help="force a JAX platform for --spawn (e.g. 'cpu')")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)

    if args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)

    members = []
    if args.spawn:
        if not args.checkpoint_dir:
            p.error("--spawn needs --checkpoint-dir")
        urls, members = _spawn_fleet(args.spawn, args)
    else:
        urls = args.replica or []
        if not urls:
            p.error("need --replica URL(s) or --spawn N")

    router = FleetRouter(
        urls,
        health_interval_s=args.health_interval_s,
        eject_after=args.eject_after,
        rollout_poll_s=args.rollout_poll_s,
        trace_log=args.trace_log,
        capacity_policy=args.capacity_policy,
        capacity_persist_windows=args.capacity_persist_windows,
    )
    router.start()
    server = make_router_server(router, args.host, args.port,
                                quiet=not args.verbose)

    stop_once = threading.Event()

    def _graceful(signum, frame):
        if stop_once.is_set():
            return
        stop_once.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    host, port = server.server_address[:2]
    print(json.dumps({
        "event": "routing", "host": host, "port": port,
        "replicas": urls,
        "healthy": router.health()["healthy_replicas"],
    }), flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        router.shutdown()
        server.server_close()
        for engine, eng_server in members:
            eng_server.shutdown()
            engine.shutdown(drain=True)
            eng_server.server_close()
        print(json.dumps({"event": "router_drained"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
