"""Shape-bucketed padded batching with an ahead-of-time compile cache.

Serving latency on XLA hardware is won by never compiling on the request
path (PAPERS.md: compiler-first inference; a cold jit cache miss costs
seconds against a millisecond forward).  The contract here:

  * a fixed, configurable set of batch-size **buckets**;
  * every incoming batch is zero-**padded up** to the nearest bucket and
    the output **sliced back** (per-image results are independent of the
    padding rows — GLOM's forward has no cross-batch reductions, so the
    sliced result is bit-identical to the unpadded forward);
  * every bucket is **AOT-compiled at startup** via
    ``jax.jit(...).lower(...).compile()`` from ``ShapeDtypeStruct``
    arguments (no device data needed), and the request path calls the
    stored executables directly — the jit dispatch path, whose cache-size
    growth is exactly what :class:`~glom_tpu.obs.monitors.RecompileMonitor`
    detects, is never entered;
  * warmup records a :func:`glom_tpu.profiling.snapshot_from_compiled`
    per bucket (HLO text + compiler cost/memory model) so the operator can
    see what each shape costs before traffic arrives;
  * with ``shardings`` set (a mesh-sharded engine —
    :mod:`glom_tpu.serving.sharded`), every bucket compiles against
    explicit in/out shardings: TP/EP-sharded params serve without the
    request path ever moving a weight, and the no-compile invariant holds
    unchanged (the monitor watches the same single jit fn).

The attached :class:`RecompileMonitor` is the tripwire for the invariant,
not a bookkeeping nicety: any code path that falls back to calling the
jitted function with an un-warmed shape shows up as jit cache growth, and
the engine exports it as ``serving_xla_compiles`` — the acceptance signal
"zero XLA recompiles after startup" is asserted against it.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from glom_tpu import profiling
from glom_tpu.obs.monitors import RecompileMonitor


def pick_bucket(buckets: Sequence[int], n: int) -> Optional[int]:
    """Smallest bucket >= ``n``, or None when ``n`` exceeds every bucket
    (``buckets`` must be sorted ascending)."""
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    i = bisect.bisect_left(buckets, n)
    return buckets[i] if i < len(buckets) else None


def pad_to_bucket(imgs: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad the batch axis up to ``bucket`` — the one padding rule,
    shared with the data-parallel forward (``parallel.inference.pad_batch``)."""
    b = imgs.shape[0]
    if b > bucket:
        raise ValueError(f"batch {b} exceeds bucket {bucket}")
    from glom_tpu.parallel.inference import pad_batch

    return pad_batch(imgs, bucket)


class BucketedCompileCache:
    """AOT-compiled executables of one forward fn, keyed by batch bucket.

    ``fn(params, imgs)`` is the raw (un-jitted) forward; the cache owns the
    single ``jax.jit`` wrapping so the recompile monitor has exactly one
    dispatch cache to watch.  :meth:`warmup` compiles every bucket;
    :meth:`__call__` pads, runs the bucket's executable, and slices.
    """

    def __init__(self, fn: Callable, buckets: Sequence[int], *,
                 name: str = "forward", quant: str = "f32",
                 donate: Optional[bool] = None,
                 shardings: Optional[Tuple[Any, Any, Any]] = None,
                 mesh_axes: Optional[dict] = None,
                 carries_state: bool = False,
                 takes_state: bool = False,
                 state_sharding: Optional[Any] = None,
                 iters: Optional[int] = None):
        buckets = sorted(set(int(b) for b in buckets))
        if not buckets:
            raise ValueError("need at least one bucket size")
        if buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {buckets[0]}")
        self.name = name
        self.buckets: Tuple[int, ...] = tuple(buckets)
        # the quant label of every entry this cache registers: one cache
        # serves one (endpoint, quant) pair, so executables compiled for
        # int8 weight trees can never be fed an f32 tree (the aval
        # mismatch would raise, but the label makes the registry legible:
        # snapshots, warmup bundles, and /healthz all carry it)
        from glom_tpu.serving.quant import QUANT_MODES

        if quant not in QUANT_MODES:
            raise ValueError(f"unknown quant label {quant!r}")
        self.quant = quant
        # donate the IMAGE buffer into the executable (params are reused
        # across requests and must never be donated): every call builds a
        # fresh padded batch, so its buffer is dead after dispatch — on
        # TPU this lets XLA alias it for the first layer's scratch.
        # None => auto: donation is a no-op (with a warning) on CPU.
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self.donates_input = bool(donate)
        # -- mesh-sharded execution (glom_tpu.serving.sharded) -------------
        # ``shardings`` = (params_sharding_tree, img_sharding, out_sharding)
        # pins every bucket's executable to an explicit partitioned layout:
        # params stay resident where the engine placed them (TP/EP shards
        # never move), the padded batch shards over the data axis on the
        # way in, and the jit boundary is the ONE place the layout is
        # stated — exactly the parallel/inference.py recipe, AOT-compiled.
        # ``mesh_axes`` ({"data": 4, ...}) labels snapshots and /healthz.
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        # -- stateful (levels-in/levels-out) buckets -----------------------
        # The session-serving shapes (glom_tpu.serving.sessions is the
        # OWNER of that state; this cache only threads an opaque array
        # through the executable):
        #   carries_state: fn returns (out, new_state) — `out` is sliced
        #     back to the real batch, the state stays BUCKET-shaped so the
        #     next frame feeds it straight back in with zero reshaping
        #     (a per-frame device pad would be a request-path compile);
        #   takes_state: fn is (params, imgs, state) and __call__ requires
        #     a bucket-shaped `state`.
        # Effectively the executables are keyed on (batch-bucket, stateful)
        # — a warm 4-batch graph and a cold 4-batch graph are distinct
        # entries that never collide.
        if takes_state and not carries_state:
            raise ValueError("takes_state requires carries_state "
                             "(a warm step must return the next state)")
        self.carries_state = bool(carries_state)
        self.takes_state = bool(takes_state)
        # `iters`/`stateful` label every execute span: the trace feed is
        # where warm-start savings become visible (tools/trace_report.py
        # splits warm vs cold execute time on exactly these attrs)
        self.iters = None if iters is None else int(iters)
        self.stateful = self.takes_state
        jit_kwargs = {"donate_argnums": (1,) if donate else ()}
        if shardings is not None:
            params_sh, img_sh, out_sh = shardings
            if carries_state:
                # the state rides the batch-axis layout (img_sh is a
                # leading-axis-only spec, rank-agnostic by construction)
                st_sh = state_sharding if state_sharding is not None else img_sh
                in_sh = ((params_sh, img_sh, st_sh) if takes_state
                         else (params_sh, img_sh))
                jit_kwargs.update(in_shardings=in_sh,
                                  out_shardings=(out_sh, st_sh))
            else:
                jit_kwargs.update(in_shardings=(params_sh, img_sh),
                                  out_shardings=out_sh)
        self._jit_fn = jax.jit(fn, **jit_kwargs)
        self._compiled: Dict[int, Any] = {}
        self.monitor = RecompileMonitor(self._jit_fn)
        self.snapshots: Dict[int, Dict[str, Any]] = {}
        self.warmed = False

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def pick(self, n: int) -> Optional[int]:
        return pick_bucket(self.buckets, n)

    # -- warmup ------------------------------------------------------------
    def warmup(self, params, img_struct_fn: Callable[[int], jax.ShapeDtypeStruct],
               *, state_struct_fn: Optional[Callable] = None,
               keep_hlo: bool = True) -> None:
        """AOT-compile every bucket.  ``params`` may be real arrays or a
        matching pytree of ``ShapeDtypeStruct`` — only shapes/dtypes reach
        the lowering; ``img_struct_fn(bucket)`` supplies the batch aval,
        and a ``takes_state`` cache additionally needs
        ``state_struct_fn(bucket)`` for the carried-state aval.

        Idempotent per bucket; records a compile snapshot (HLO optional via
        ``keep_hlo`` — it can run to MBs for big models) for each."""
        if self.takes_state and state_struct_fn is None:
            raise ValueError(f"cache {self.name!r} takes_state: warmup "
                             f"needs state_struct_fn")
        params_struct = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(np.shape(p), p.dtype), params
        )
        for bucket in self.buckets:
            if bucket in self._compiled:
                continue
            args = (params_struct, img_struct_fn(bucket))
            if self.takes_state:
                args += (state_struct_fn(bucket),)
            lowered = self._jit_fn.lower(*args)
            compiled = lowered.compile()
            self._compiled[bucket] = compiled
            snap = profiling.snapshot_from_compiled(lowered, compiled)
            if not keep_hlo:
                snap.pop("hlo", None)
            # each registered entry carries its quant label: an operator
            # reading warmup bundles can tell an int8 executable's cost
            # model from the f32 one's at a glance
            snap["quant"] = self.quant
            if self.carries_state:
                snap["stateful"] = self.takes_state
            if self.iters is not None:
                snap["iters"] = self.iters
            if self.mesh_axes:
                snap["mesh"] = dict(self.mesh_axes)
            self.snapshots[bucket] = snap
        # baseline the monitor AFTER warmup: AOT lower/compile never touches
        # the jit dispatch cache, but a zero poll here makes that explicit —
        # every later nonzero poll is a request-path compile
        self.monitor.poll()
        self.warmed = True

    # -- request path ------------------------------------------------------
    def _fallback_imgs(self, imgs, state):
        """Batch axis for the jit-dispatch fallback: a carried state may
        be BUCKET-shaped (a spill restored under --no-warmup, or a
        warmed-then-fallback mix) while ``imgs`` is the raw request
        batch — the two must agree or apply() rejects the mismatched
        axes, so the fallback pads images up to the state's batch."""
        if (self.takes_state and state is not None
                and state.shape[0] != imgs.shape[0]):
            return pad_to_bucket(imgs, state.shape[0])
        return imgs

    def _run(self, params, imgs, state, bucket):
        """One executable dispatch (AOT when warmed, jit fallback
        otherwise) — the state, when this cache takes one, is already
        bucket-shaped by the caller's contract."""
        aot = bucket is not None and bucket in self._compiled
        if aot:
            args = (params, pad_to_bucket(imgs, bucket))
        else:
            args = (params, self._fallback_imgs(imgs, state))
        if self.takes_state:
            args += (state,)
        fn = self._compiled[bucket] if aot else self._jit_fn
        return fn(*args), aot

    def _slice_back(self, out, b):
        """Slice the batch axis back to the real ``b``.  A carries_state
        output is ``(y, new_state)``: only ``y`` is sliced — the state
        stays bucket-shaped on purpose (it is the next frame's executable
        input; see the class docstring)."""
        if self.carries_state:
            y, new_state = out
            if y.shape[0] != b:
                y = y[:b]
            return y, new_state
        return out[:b] if out.shape[0] != b else out

    def __call__(self, params, imgs: np.ndarray, *, state=None, tracer=None,
                 contexts: Sequence = ()):
        """Pad ``imgs`` to its bucket, run, slice the batch axis back.

        A batch over the largest bucket falls back to the jit dispatch path
        (correct, but it may compile — the monitor and the engine's
        ``serving_xla_compiles`` counter record it).  Engines prevent this
        by capping the batcher's ``max_batch`` at the largest bucket.

        With a ``tracer``, records ``bucket_select`` / ``pad`` /
        ``execute`` spans — annotated with the bucket shape, padding
        waste, ``iters`` and ``stateful`` — under every span context in
        ``contexts`` (the batch-level span first, then each member
        request: one physical operation fans into every trace that paid
        for it; only the first context feeds the duration histograms).
        Tracing makes ``execute`` block until the device result is ready
        — the span must hold device time, not dispatch time; the untraced
        path keeps async dispatch."""
        b = imgs.shape[0]
        bucket = self.pick(b)
        if self.takes_state and state is None:
            raise ValueError(f"cache {self.name!r} takes_state: __call__ "
                             f"needs state=")
        extra = (state,) if self.takes_state else ()
        if tracer is None or not contexts:
            out, _ = self._run(params, imgs, state, bucket)
            return self._slice_back(out, b)

        clock = tracer.clock
        t0 = clock()          # bucket already picked above: charge ~0
        aot = bucket is not None and bucket in self._compiled
        if aot:
            padded = pad_to_bucket(imgs, bucket)
            t_pad = clock()
            out = self._compiled[bucket](params, padded, *extra)
        else:
            t_pad = t0
            out = self._jit_fn(params, self._fallback_imgs(imgs, state),
                               *extra)
        # slice INSIDE the execute span: the batch-axis slice is a jax op
        # (it pays a one-off compile per new output shape) and the span
        # must hold everything between padded input and usable result
        out = self._slice_back(out, b)
        jax.block_until_ready(out)  # glomlint: disable=jax-host-sync -- the execute span's contract: latency is recorded only once the result is device-complete

        t_done = clock()
        # a jit-dispatch fallback has NO bucket: labeling it with the raw
        # batch size would mint one serving_execute_ms_b<n> metric per
        # distinct fallback size (unbounded cardinality) and fake rows in
        # the per-bucket padding-waste table
        attrs = {"images": b, "aot": aot, "endpoint": self.name,
                 "stateful": self.stateful}
        if self.iters is not None:
            attrs["iters"] = self.iters
        if aot:
            attrs["bucket"] = bucket
            attrs["padding_waste"] = round((bucket - b) / bucket, 4)
        from glom_tpu.obs.tracing import SPAN_BUCKET_SELECT, SPAN_EXECUTE, SPAN_PAD

        for i, ctx in enumerate(contexts):
            observe = i == 0
            tracer.record(SPAN_BUCKET_SELECT, ctx, t0, t0,
                          attrs={"bucket": bucket if aot else None,
                                 "aot": aot},
                          observe=observe)
            tracer.record(SPAN_PAD, ctx, t0, t_pad, attrs=dict(attrs),
                          observe=observe)
            tracer.record(SPAN_EXECUTE, ctx, t_pad, t_done, attrs=dict(attrs),
                          observe=observe)
        return out

    def poll_compiles(self) -> int:
        """New jit-dispatch compiles since the last poll — nonzero after
        warmup means the no-compile-on-request-path invariant broke."""
        return self.monitor.poll()


class PostPassCache:
    """An endpoint that is another endpoint's output plus a cheap traced
    post-pass, sharing the inner cache's executables.

    ``/parse`` is the motivating case: its forward is the ``index``
    endpoint's settle followed by the islanding pack.  Compiling that as
    its own :class:`BucketedCompileCache` family duplicates the settle
    graph — roughly doubling warmup wall time per bucket for a post-pass
    whose own graph lowers in milliseconds.  This wrapper instead pads
    the batch up-front (so the inner cache runs at exactly bucket shape
    and hands back a bucket-shaped output), applies an AOT-compiled
    post-pass keyed by the intermediate's aval, and slices the batch
    axis back itself.

    Quacks like :class:`BucketedCompileCache` for everything the engine
    touches (``pick``/``buckets``/``warmup``/``__call__``/
    ``poll_compiles``/``snapshots``); trace spans come from the inner
    cache, so execute time shows under the inner endpoint's name — the
    honest attribution, since that is the graph doing the work.
    ``warm_aval`` admits extra intermediate avals (the session caches'
    carried state rides the same post-pass at its own dtype).
    """

    def __init__(self, inner: BucketedCompileCache, post_fn: Callable,
                 post_struct_fn: Callable[[int], jax.ShapeDtypeStruct], *,
                 name: str, sharding: Optional[Any] = None):
        self.inner = inner
        self.name = name
        self.quant = inner.quant
        self.buckets = inner.buckets
        self.donates_input = inner.donates_input
        self.mesh_axes = inner.mesh_axes
        self.carries_state = False
        self.takes_state = False
        self.stateful = False
        self.iters = inner.iters
        kwargs = {}
        if sharding is not None:
            # the intermediate and the packed rows both ride the batch
            # axis: one leading-axis spec covers input and output
            kwargs.update(in_shardings=(sharding,), out_shardings=sharding)
        self._jit_fn = jax.jit(post_fn, **kwargs)
        self._post_struct_fn = post_struct_fn
        self._compiled: Dict[Tuple, Any] = {}
        self.monitor = RecompileMonitor(self._jit_fn)
        self.snapshots: Dict[int, Dict[str, Any]] = {}
        self.warmed = False

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def pick(self, n: int) -> Optional[int]:
        return pick_bucket(self.buckets, n)

    def warm_aval(self, struct: jax.ShapeDtypeStruct) -> None:
        """AOT-compile the post-pass for one intermediate aval
        (idempotent) — the request path then never enters jit dispatch
        for that shape/dtype."""
        key = (tuple(struct.shape), np.dtype(struct.dtype).str)
        if key not in self._compiled:
            self._compiled[key] = self._jit_fn.lower(struct).compile()

    def warmup(self, params, img_struct_fn, *, state_struct_fn=None,
               keep_hlo: bool = True) -> None:
        """Warm the inner cache (idempotent — it may already have warmed
        under its own endpoint name) plus the post-pass per bucket."""
        del state_struct_fn  # stateless by construction
        if not self.inner.warmed:
            self.inner.warmup(params, img_struct_fn, keep_hlo=keep_hlo)
        for bucket in self.buckets:
            self.warm_aval(self._post_struct_fn(bucket))
        self.monitor.poll()
        self.warmed = True

    def apply_post(self, intermediate):
        """Run the post-pass alone on an already-computed intermediate
        (the ``/session/parse`` path: the session executables produced
        the carried state; only the pack remains).  Unknown avals fall
        back to jit dispatch — correct, and ``poll_compiles`` reports
        the compile."""
        key = (tuple(intermediate.shape), np.dtype(intermediate.dtype).str)
        exe = self._compiled.get(key)
        if exe is not None:
            return exe(intermediate)
        return self._jit_fn(intermediate)

    def __call__(self, params, imgs: np.ndarray, *, state=None, tracer=None,
                 contexts: Sequence = ()):
        del state
        b = imgs.shape[0]
        bucket = self.pick(b)
        if bucket is not None:
            imgs = pad_to_bucket(imgs, bucket)
        # the inner call sees a batch exactly at bucket size, so its own
        # slice-back is a no-op and the intermediate keeps the warmed
        # bucket aval; over-max batches ride the inner jit fallback and
        # the post-pass jit fallback, both monitored
        intermediate = self.inner(params, imgs, tracer=tracer,
                                  contexts=contexts)
        out = self.apply_post(intermediate)
        return out[:b] if out.shape[0] != b else out

    def poll_compiles(self) -> int:
        """Post-pass dispatch compiles PLUS the inner cache's — whichever
        accounting site polls first claims them; the shared counter sums
        to the same ``serving_xla_compiles`` either way."""
        return self.monitor.poll() + self.inner.poll_compiles()
