"""Multi-tenant model registry: named models/versions resident at once.

One :class:`~glom_tpu.serving.engine.ServingEngine` used to own exactly
one param tree of one checkpoint lineage.  The registry generalizes that
into the safe-deploy substrate (ROADMAP item 4):

  * **residency** — multiple :class:`ModelVersion` records live side by
    side, each naming a ``(model, step)`` pair with its own placed param
    tree, quant mode, config, and compile-cache bucket namespace.  The
    engine's serving tree is the ``default`` model's ``primary`` record;
    a deploy candidate (:mod:`glom_tpu.serving.deploy`) is a second
    resident version of the same model; extra models (other checkpoints,
    other configs, other quant modes) load independently;

  * **compile-cache bucket namespaces with AOT reuse** — every version
    owns a ``{endpoint: BucketedCompileCache}`` namespace, but two
    versions whose :meth:`cache_signature` matches (same config, quant,
    buckets, kernel choice, mesh) ALIAS one set of compiled executables:
    params are executable *arguments*, so a new checkpoint of the same
    model serves through the already-warm AOT entries with zero new
    compiles — what makes a resident candidate cheap enough to shadow
    (the pjit/TPUv4 AOT-reuse argument, arXiv:2204.06514).  A version
    whose signature differs gets its own freshly-warmed namespace;

  * **lineage tracking anchored on ``integrity.latest_valid_step``** —
    each model records its checkpoint directory, and
    :meth:`ModelRegistry.lineage` reports the newest step that VERIFIES
    alongside the resident steps and the promote/retire history: a
    deploy can only target a step the integrity scan vouches for, and
    the anchor is the same one the hot-reload watcher and the trainer's
    auto-resume trust.

Host-side bookkeeping only (the param trees it holds are opaque
references); injectable clock; every mutation is lock-serialized, reads
return snapshots.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from glom_tpu.obs import MetricRegistry

#: the engine's own model name in its registry
DEFAULT_MODEL = "default"

ROLES = ("primary", "candidate", "resident")


def cache_signature(config, quant: str, buckets, *, iters=None,
                    mesh_axes: Optional[dict] = None) -> Tuple:
    """The executable-identity key: two versions with equal signatures
    produce identical jit avals/HLO, so their compile-cache namespaces
    may alias (params are call arguments, not compile-time constants)."""
    return (
        tuple(sorted(config.to_json_dict().items(),
                     key=lambda kv: kv[0])),
        str(quant),
        tuple(int(b) for b in buckets),
        None if iters is None else int(iters),
        tuple(sorted((mesh_axes or {}).items())),
    )


@dataclass
class ModelVersion:
    """One resident ``(model, step)``: placed params + cache namespace."""

    model: str
    step: int
    quant: str
    params: Any                      # placed (device) param tree
    caches: Dict[str, Any]           # endpoint -> BucketedCompileCache
    config: Any                      # GlomConfig the params serve under
    train_cfg: Any = None            # recorded TrainConfig (decode path)
    signature: Tuple = ()
    source_dir: Optional[str] = None
    role: str = "resident"
    aliased: bool = False            # caches shared with another version
    loaded_at: float = 0.0

    def summary(self) -> dict:
        return {
            "model": self.model,
            "step": int(self.step),
            "quant": self.quant,
            "role": self.role,
            "cache_aliased": bool(self.aliased),
            "endpoints": sorted(self.caches),
            "loaded_at": round(self.loaded_at, 3),
        }


class ModelRegistry:
    """Residency + lineage bookkeeping for every loaded (model, step).

    The engine registers its startup tree as ``(DEFAULT_MODEL, step,
    role="primary")`` and keeps the record in sync across hot reloads /
    staged commits (:meth:`sync_primary`); the deploy controller adds and
    retires ``role="candidate"`` records; extra models register under
    their own names.  ``max_versions_per_model`` bounds residency — every
    resident version is a full device param tree, so an unbounded
    registry is an OOM, not a feature."""

    def __init__(self, *, registry: Optional[MetricRegistry] = None,
                 clock=None, max_versions_per_model: int = 3,
                 history: int = 32):
        self.metrics = registry if registry is not None else MetricRegistry()
        self._clock = clock if clock is not None else time.monotonic
        if max_versions_per_model < 2:
            # primary + one candidate is the minimum a deploy needs
            raise ValueError(
                f"max_versions_per_model must be >= 2, got "
                f"{max_versions_per_model}")
        self.max_versions_per_model = max_versions_per_model
        self._lock = threading.Lock()
        self._versions: Dict[Tuple[str, int], ModelVersion] = {}
        self._dirs: Dict[str, str] = {}      # model -> checkpoint dir
        self._history: "deque" = deque(maxlen=history)

    # -- residency ---------------------------------------------------------
    def register(self, model: str, step: int, *, params, caches,
                 config, quant: str, signature: Tuple = (),
                 train_cfg=None, source_dir: Optional[str] = None,
                 role: str = "resident", aliased: bool = False
                 ) -> ModelVersion:
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; one of {ROLES}")
        step = int(step)
        with self._lock:
            key = (model, step)
            if key in self._versions:
                raise ValueError(f"{model}@{step} is already resident")
            mine = [v for v in self._versions.values() if v.model == model]
            if len(mine) >= self.max_versions_per_model:
                raise ValueError(
                    f"model {model!r} already holds "
                    f"{len(mine)} resident versions (max "
                    f"{self.max_versions_per_model}); retire one first — "
                    f"each version is a full device param tree")
            if role == "primary":
                for v in mine:
                    if v.role == "primary":
                        raise ValueError(
                            f"{model} already has primary @{v.step}; use "
                            f"promote()/sync_primary()")
            version = ModelVersion(
                model=model, step=step, quant=quant, params=params,
                caches=dict(caches), config=config, train_cfg=train_cfg,
                signature=signature, source_dir=source_dir, role=role,
                aliased=aliased, loaded_at=self._clock(),
            )
            self._versions[key] = version
            if source_dir:
                self._dirs.setdefault(model, source_dir)
            self._note("register", model, step, role=role, aliased=aliased)
        self._gauges()
        if aliased:
            self.metrics.counter(
                "registry_cache_alias_total",
                help="resident versions serving through another version's "
                     "AOT compile-cache namespace (zero new compiles)",
            ).inc()
        return version

    def find_alias(self, model: str, signature: Tuple
                   ) -> Optional[ModelVersion]:
        """A resident version of ``model`` whose executable signature
        matches — its caches may be shared by a new version."""
        with self._lock:
            for v in self._versions.values():
                if v.model == model and v.signature == signature:
                    return v
        return None

    def get(self, model: str, step: Optional[int] = None
            ) -> Optional[ModelVersion]:
        """``step=None`` -> the model's primary."""
        with self._lock:
            if step is not None:
                return self._versions.get((model, int(step)))
            for v in self._versions.values():
                if v.model == model and v.role == "primary":
                    return v
        return None

    def versions(self, model: Optional[str] = None) -> List[ModelVersion]:
        with self._lock:
            out = [v for v in self._versions.values()
                   if model is None or v.model == model]
        return sorted(out, key=lambda v: (v.model, v.step))

    def models(self) -> List[str]:
        with self._lock:
            return sorted({v.model for v in self._versions.values()})

    def remove(self, model: str, step: int) -> bool:
        """Retire one resident version (its params reference is dropped —
        the device memory frees when the last in-flight batch that
        snapshotted it completes)."""
        with self._lock:
            version = self._versions.pop((model, int(step)), None)
            if version is not None:
                self._note("retire", model, int(step), role=version.role)
        self._gauges()
        return version is not None

    # -- primary transitions ----------------------------------------------
    def promote(self, model: str, step: int) -> ModelVersion:
        """The resident ``(model, step)`` becomes primary; the displaced
        primary record is retired (the ENGINE keeps its own rollback
        reference — registry residency is about what serves, not about
        undo)."""
        with self._lock:
            version = self._versions.get((model, int(step)))
            if version is None:
                raise KeyError(f"{model}@{step} is not resident")
            for v in list(self._versions.values()):
                if v.model == model and v.role == "primary":
                    del self._versions[(model, v.step)]
                    self._note("retire", model, v.step, role="displaced")
            version.role = "primary"
            self._note("promote", model, int(step))
        self._gauges()
        return version

    def sync_primary(self, model: str, step: int, params,
                     *, source: str = "reload") -> None:
        """The engine's param swap paths (hot reload, staged commit,
        rollback) re-anchor the primary record here so the registry view
        never drifts from what actually serves.  The old primary's caches
        carry over (same signature by construction — a reload re-places
        the same config/quant)."""
        step = int(step)
        with self._lock:
            old = None
            for v in list(self._versions.values()):
                if v.model == model and v.role == "primary":
                    old = v
                    del self._versions[(model, v.step)]
            # a swap targeting an already-resident step (rollback onto a
            # still-resident candidate record) adopts that record
            existing = self._versions.get((model, step))
            if existing is not None:
                existing.role = "primary"
                existing.params = params
            elif old is not None:
                old.step = step
                old.params = params
                old.loaded_at = self._clock()
                self._versions[(model, step)] = old
            self._note("sync_primary", model, step, source=source)
        self._gauges()

    # -- lineage -----------------------------------------------------------
    def lineage(self, model: str) -> dict:
        """Checkpoint-lineage view anchored on the integrity scan: the
        newest step that VERIFIES in the model's checkpoint dir, the
        resident steps, and the recent transition history."""
        from glom_tpu.resilience import integrity

        with self._lock:
            source_dir = self._dirs.get(model)
            resident = sorted(v.step for v in self._versions.values()
                              if v.model == model)
            primary = next((v.step for v in self._versions.values()
                            if v.model == model and v.role == "primary"),
                           None)
            history = [h for h in self._history if h["model"] == model]
        latest_valid = None
        if source_dir:
            # quarantine_corrupt=False: a lineage READ must not mutate
            # the checkpoint dir — quarantine stays the watcher's call
            latest_valid = integrity.latest_valid_step(
                source_dir, quarantine_corrupt=False)
        return {
            "model": model,
            "checkpoint_dir": source_dir,
            "latest_valid_step": latest_valid,
            "primary_step": primary,
            "resident_steps": resident,
            "history": history,
        }

    # -- views -------------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/healthz`` ``models`` block."""
        with self._lock:
            versions = [v.summary() for v in self._versions.values()]
        versions.sort(key=lambda s: (s["model"], s["step"]))
        return {
            "resident": versions,
            "models": sorted({s["model"] for s in versions}),
        }

    def _note(self, event: str, model: str, step: int, **fields) -> None:
        # caller holds the lock
        self._history.append({
            "event": event, "model": model, "step": int(step),
            "t": round(self._clock(), 6), **fields,
        })

    def _gauges(self) -> None:
        with self._lock:
            by_model: Dict[str, int] = {}
            for v in self._versions.values():
                by_model[v.model] = by_model.get(v.model, 0) + 1
            total = len(self._versions)
        self.metrics.gauge(
            "registry_resident_versions",
            help="model versions resident (each a full device param tree)",
        ).set(total)
        for model, count in by_model.items():
            self.metrics.gauge(
                self.metrics.labeled("registry_resident_versions_", model),
                help="resident versions of one model",
            ).set(count)


# ---------------------------------------------------------------------------
# standalone loading: a full (params + warmed cache namespace) version
# from a checkpoint dir, without a ServingEngine
# ---------------------------------------------------------------------------
def load_version(model: str, checkpoint_dir: str, *,
                 buckets=(1, 2, 4, 8), quant: str = "f32",
                 iters: Optional[int] = None,
                 step: Optional[int] = None,
                 donate: Optional[bool] = None,
                 warmup: bool = True,
                 models: Optional[ModelRegistry] = None,
                 role: str = "resident") -> ModelVersion:
    """Load ``(model, step)`` from a Trainer checkpoint dir into a fully
    servable :class:`ModelVersion`: quantized + placed params and an
    embed/reconstruct compile-cache namespace, AOT-warmed unless an
    already-resident version with the same :func:`cache_signature` can
    be aliased (``models`` passed).  ``step=None`` anchors on the newest
    step that verifies — the same ``integrity.latest_valid_step`` rule
    the engine's watcher trusts."""
    import jax
    import numpy as np

    from glom_tpu.serving import quant as serving_quant
    from glom_tpu.serving.compile_cache import (
        BucketedCompileCache,
        PostPassCache,
    )
    from glom_tpu.training import denoise

    loaded_step, config, train_cfg, host_params = (
        denoise.load_checkpoint_state(checkpoint_dir, step=step))
    serve_cfg = serving_quant.serving_config(config, quant)
    placed = jax.device_put(
        serving_quant.quantize_tree(host_params, quant))
    signature = cache_signature(config, quant, buckets, iters=iters)

    alias = models.find_alias(model, signature) if models is not None else None
    if alias is not None:
        caches, aliased = alias.caches, True
    else:
        from glom_tpu.hierarchy import parse as hierarchy_parse
        from glom_tpu.serving.engine import (
            _make_embed_fn,
            _make_reconstruct_fn,
        )

        caches = {
            "embed": BucketedCompileCache(
                serving_quant.quantized_forward(
                    _make_embed_fn(serve_cfg, iters), quant),
                buckets, name="embed", quant=quant, donate=donate),
            "reconstruct": BucketedCompileCache(
                serving_quant.quantized_forward(
                    _make_reconstruct_fn(serve_cfg, train_cfg, iters),
                    quant),
                buckets, name="reconstruct", quant=quant, donate=donate),
            # the part-whole plane serves non-default models too: /parse
            # requests may pin a model, and registry-pinned bulk "index"
            # jobs execute against the pin's own cache namespace
            "index": BucketedCompileCache(
                serving_quant.quantized_forward(
                    hierarchy_parse.make_index_fn(serve_cfg, iters), quant),
                buckets, name="index", quant=quant, donate=donate),
        }
        # /parse rides the index executables + the islanding post-pass
        # (PostPassCache) — the settle graph compiles once per bucket
        # for this version's whole cache namespace
        c = serve_cfg
        caches["parse"] = PostPassCache(
            caches["index"],
            hierarchy_parse.make_pack_fn(
                serve_cfg,
                hierarchy_parse.parse_thresholds(None, serve_cfg.levels)),
            lambda b: jax.ShapeDtypeStruct(
                (b, c.num_patches, c.levels, c.dim), np.float32),
            name="parse")
        aliased = False
        if warmup:
            c = serve_cfg
            for cache in caches.values():
                cache.warmup(placed, lambda b: jax.ShapeDtypeStruct(
                    (b, c.channels, c.image_size, c.image_size),
                    np.float32))

    version_kwargs = dict(
        params=placed, caches=caches, config=serve_cfg,
        train_cfg=train_cfg, signature=signature,
        source_dir=checkpoint_dir, role=role, aliased=aliased,
        quant=quant,
    )
    if models is not None:
        return models.register(model, loaded_step, **version_kwargs)
    return ModelVersion(model=model, step=int(loaded_step),
                        loaded_at=0.0, **version_kwargs)
