"""The scavenger execution class: bulk jobs on residual online capacity.

Online serving pads every batch up to its AOT bucket; those padding rows
execute anyway and their results are thrown away.  The
:class:`BulkRunner` turns them into throughput: when the engine's
execute loop assembles an online group of ``n`` images against bucket
``b``, it asks the runner for up to ``b - n`` bulk samples and runs the
FULL bucket through the already-warmed executable — the bulk rows ride
device work that was already paid for.  Idle flush windows (batcher
depth zero) run whole bulk buckets the same way.

Priority rules (docs/BULK.md):

  * **Online always wins.**  Bulk never enters the batcher, never takes
    admission, and the idle loop refuses to start a batch while ANY
    online work is queued — preemption is at the admission boundary, so
    the worst case an online request waits behind bulk is one in-flight
    bucket execution.
  * **No new compile-cache entries.**  Bulk executes the exact warmed
    ``(bucket, quant)`` executables; the shared ``serving_xla_compiles``
    counter stays 0 (polled here too, so a regression fails the same
    acceptance every endpoint is held to).
  * **Invisible to online accounting.**  Bulk slots never touch
    ``serving_requests_total``, tenant quotas, SLO evaluators, shadow
    mirroring, or quality sampling; they mint their own ``bulk_*``
    family.  The glomlint ``bulk-isolation`` rule pins the import
    boundary.

Exactly-once rides the job store's sink-then-cursor order
(:mod:`glom_tpu.bulk.jobs`): ``fill()`` stages slots in memory only;
``complete()`` writes the part file then durably advances the cursor;
``abandon()`` (failed batch, shutdown) rewinds the stage.  A kill at
ANY point re-executes at most the staged chunk, rewriting identical
bytes.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from glom_tpu.bulk.jobs import BulkJobSpec, ChunkSink, JobStore, SlotDataset

#: idle-loop poll cadence while there is nothing runnable
DEFAULT_IDLE_POLL_S = 0.002


@dataclass
class _FillToken:
    """One staged chunk: handed out by :meth:`BulkRunner.fill`, settled
    by exactly one of ``complete``/``abandon``."""

    job: str
    shard_lo: int
    lo: int
    hi: int
    imgs: np.ndarray
    source: str  # "scavenged" | "idle"


class _ActiveJob:
    """In-memory face of one store job: dataset + sink handles and the
    per-shard staging pointers.  ``staged`` runs ahead of the durable
    cursor by at most one in-flight chunk per shard (``busy`` enforces
    it), which is what keeps cursor advances strictly sequential."""

    def __init__(self, spec: BulkJobSpec, doc: dict):
        self.spec = spec
        self.dataset = SlotDataset(spec)
        self.sink = ChunkSink(spec.sink)
        self.total = int(doc["total"])
        self.paused = doc["status"] == "paused"
        # shard_lo -> {"cursor", "hi", "staged", "busy"}
        self.shards: Dict[int, Dict[str, Any]] = {}
        self.sync_shards(doc)

    def sync_shards(self, doc: dict) -> None:
        for s in doc["shards"]:
            have = self.shards.get(s["lo"])
            if have is None:
                self.shards[s["lo"]] = {
                    "cursor": int(s["cursor"]), "hi": int(s["hi"]),
                    "staged": int(s["cursor"]), "busy": False,
                }
            else:
                have["hi"] = int(s["hi"])

    @property
    def remaining(self) -> int:
        return sum(s["hi"] - s["cursor"] for s in self.shards.values())

    def next_chunk(self, k: int) -> Optional[Dict[str, Any]]:
        """Reserve up to ``k`` slots from the first shard with staged
        headroom; caller holds the runner lock."""
        if self.paused or k < 1:
            return None
        for lo, s in sorted(self.shards.items()):
            if s["busy"] or s["staged"] >= s["hi"]:
                continue
            hi = min(s["staged"] + k, s["hi"])
            chunk = {"shard_lo": lo, "lo": s["staged"], "hi": hi}
            s["busy"] = True
            s["staged"] = hi
            return chunk
        return None


class BulkRunner:
    """Scavenger-class bulk executor attached to one
    :class:`~glom_tpu.serving.engine.ServingEngine`.

    Owns the replica's :class:`~glom_tpu.bulk.jobs.JobStore` (adopting
    unfinished jobs on construction — THAT is resume-after-kill: a fresh
    engine over the same store directory picks up every durable cursor
    with zero operator action) and the idle-window thread.  The engine's
    execute loop calls :meth:`fill`/:meth:`complete` around its primary
    group to scavenge residual bucket padding."""

    def __init__(self, engine, store_root: str, *,
                 idle_poll_s: float = DEFAULT_IDLE_POLL_S,
                 clock=None):
        self.engine = engine
        self.store = JobStore(store_root)
        self.registry = engine.registry
        self.idle_poll_s = idle_poll_s
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._jobs: Dict[str, _ActiveJob] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (t, slots_done) samples for the scavenged-slots/s + ETA view;
        # bounded ring — the runner must not grow with job size
        self._progress: deque = deque(maxlen=64)
        self._slots_done = 0
        for name in self.store.names():
            doc = self.store.load(name)
            if doc["status"] in ("pending", "running", "paused"):
                try:
                    self._activate(name, doc)
                except ValueError as e:
                    # adopt-on-resume must not fail engine startup: a job
                    # pinned to a (model, version) that is not resident
                    # yet parks in the store; `resume` re-activates it
                    # once the operator load_version's the pin
                    warnings.warn(
                        f"bulk job {name!r} not adopted ({e}); parked "
                        f"until resumed", stacklevel=2)
                    self.registry.counter(
                        "bulk_jobs_parked_total",
                        help="store jobs skipped at adoption (pinned "
                             "version not resident / stale spec)",
                    ).inc()
        self._gauge_backlog()

    # -- model/version resolution -------------------------------------------
    def _resolve_version(self, spec: BulkJobSpec):
        """The (params, caches, config) a job executes against.

        Unpinned specs (``model="default"``, no version) ride the LIVE
        primary — re-read per batch, so promotions apply to bulk too.  A
        pinned (model, version) must be resident in the model registry;
        its aliased AOT caches keep the zero-compile invariant, and the
        attribution plane can then blame device time on that exact
        version instead of lumping it in with online traffic."""
        engine = self.engine
        if spec.model == "default" and spec.version is None:
            return engine.params, engine.caches, engine.config
        version = engine.models.get(
            spec.model, None if spec.version is None else int(spec.version))
        if version is None:
            raise ValueError(
                f"pin ({spec.model!r}, {spec.version!r}) is not resident "
                f"in the model registry; load_version it first")
        return version.params, version.caches, version.config

    def _pinned(self, spec: BulkJobSpec) -> bool:
        return spec.model != "default" or spec.version is not None

    # -- job admin (the /admin/jobs/* verbs) -------------------------------
    def _activate(self, name: str, doc: dict) -> None:
        spec = BulkJobSpec.from_json_dict(doc["spec"])
        _, caches, cfg = self._resolve_version(spec)
        if spec.transform not in caches:
            raise ValueError(
                f"job {name!r} transform {spec.transform!r} not served "
                f"by this engine")
        if (spec.image_size != cfg.image_size
                or spec.channels != cfg.channels):
            raise ValueError(
                f"job {name!r} geometry ({spec.channels}, "
                f"{spec.image_size}) does not match the served model "
                f"({cfg.channels}, {cfg.image_size})")
        with self._lock:
            job = self._jobs.get(name)
            if job is None:
                self._jobs[name] = _ActiveJob(spec, doc)
            else:
                job.sync_shards(doc)
                job.paused = doc["status"] == "paused"

    def submit(self, payload: dict) -> dict:
        """Create/extend a job from an ``/admin/jobs/submit`` body and
        activate it.  ``shard`` (``[lo, hi]``) scopes this replica to a
        fleet partition; ``total`` defaults to the dataset length."""
        fields = {k: payload[k] for k in (
            "name", "dataset", "transform", "sink", "model", "version",
            "seed", "image_size", "channels") if k in payload}
        cfg = self.engine.config
        fields.setdefault("image_size", int(cfg.image_size))
        fields.setdefault("channels", int(cfg.channels))
        spec = BulkJobSpec(**fields)
        # resolve the pin BEFORE anything durable is written: a job
        # against a version that is not resident must fail the submit,
        # not park a half-created store entry
        _, vcaches, vcfg = self._resolve_version(spec)
        if spec.transform not in vcaches:
            raise ValueError(
                f"transform {spec.transform!r} not served by pin "
                f"({spec.model!r}, {spec.version!r})")
        if (spec.image_size != vcfg.image_size
                or spec.channels != vcfg.channels):
            raise ValueError(
                f"job geometry ({spec.channels}, {spec.image_size}) does "
                f"not match the served model "
                f"({vcfg.channels}, {vcfg.image_size})")
        probe = SlotDataset(spec)  # validates the dataset spec eagerly
        total = int(payload.get("total", len(probe)))
        if total > len(probe):
            raise ValueError(
                f"total {total} exceeds dataset length {len(probe)}")
        shard = payload.get("shard")
        shards = [tuple(int(v) for v in shard)] if shard else None
        doc = self.store.submit(spec, total=total, shards=shards,
                                owner=str(payload.get("owner", "local")))
        self._activate(spec.name, doc)
        self._note("bulk_submit", name=spec.name, model=spec.model,
                   version=spec.version, endpoint=spec.transform,
                   total=total)
        self._gauge_backlog()
        return self.status(spec.name)

    def pause(self, name: str) -> dict:
        self.store.set_status(name, "paused")
        with self._lock:
            if name in self._jobs:
                self._jobs[name].paused = True
        return self.status(name)

    def resume(self, name: str) -> dict:
        doc = self.store.set_status(name, "running")
        self._activate(name, doc)
        with self._lock:
            self._jobs[name].paused = False
        self._note("bulk_resume", name=name)
        return self.status(name)

    def _note(self, event: str, **fields) -> None:
        """Unified timeline record (obs.events): bulk activity carries
        its (model, version) pin so attribution can tell a bulk job on a
        pinned version apart from online traffic."""
        timeline = getattr(self.engine, "timeline", None)
        if timeline is not None:
            timeline.note(event, **fields)

    def cancel(self, name: str) -> dict:
        self.store.set_status(name, "cancelled")
        with self._lock:
            self._jobs.pop(name, None)
        self._gauge_backlog()
        return self.status(name)

    def status(self, name: Optional[str] = None) -> dict:
        if name is not None:
            return self.store.status(name)
        return self.summary()

    # -- the scavenger fill/complete/abandon cycle --------------------------
    def fill(self, endpoint: str, k: int, source: str = "scavenged",
             job_name: Optional[str] = None) -> Optional[_FillToken]:
        """Stage up to ``k`` slots of some runnable job whose transform
        is ``endpoint``.  Returns None when nothing is runnable — the
        overwhelmingly common case, kept to a dict scan.  The staged
        chunk is NOT durable: only :meth:`complete` commits it.

        Scavenged fills ride the ONLINE batch's executable — i.e. the
        live primary — so jobs pinned to another (model, version) are
        never scavenged; they only run in idle windows, where the idle
        loop names the job (``job_name``) and executes the pin's own
        params/caches."""
        if k < 1:
            return None
        with self._lock:
            for name, job in self._jobs.items():
                if job.spec.transform != endpoint:
                    continue
                if job_name is not None and name != job_name:
                    continue
                if source == "scavenged" and self._pinned(job.spec):
                    continue
                chunk = job.next_chunk(k)
                if chunk is not None:
                    break
            else:
                return None
            dataset = job.dataset
        # materialize OUTSIDE the lock: the shard's busy flag protects
        # the range, and dataset reads are pure functions of the slots
        imgs = dataset.read(chunk["lo"], chunk["hi"])
        return _FillToken(job=name, shard_lo=chunk["shard_lo"],
                          lo=chunk["lo"], hi=chunk["hi"], imgs=imgs,
                          source=source)

    def complete(self, token: _FillToken, out: np.ndarray) -> None:
        """Commit one executed chunk: part file first, cursor second
        (the exactly-once order), then release the shard."""
        with self._lock:
            job = self._jobs.get(token.job)
        if job is None:  # cancelled while in flight: drop the output
            return
        if job.spec.transform == "index":
            # the similarity-index build publishes PER-LEVEL part
            # families (same tmp+rename + orphan-overlap idempotence,
            # same sink directory) instead of the flat ChunkSink layout
            from glom_tpu.hierarchy.index import write_index_parts

            write_index_parts(job.sink.root, token.lo, token.hi,
                              np.asarray(out))
        else:
            job.sink.write(token.lo, token.hi, np.asarray(out))
        doc = self.store.advance(token.job, token.shard_lo, token.hi)
        n = token.hi - token.lo
        with self._lock:
            shard = job.shards[token.shard_lo]
            shard["cursor"] = token.hi
            shard["busy"] = False
            self._slots_done += n
            self._progress.append((self._clock(), self._slots_done))
            if doc["status"] == "done":
                self._jobs.pop(token.job, None)
        reg = self.registry
        reg.counter("bulk_slots_total",
                    help="bulk samples executed (all sources)").inc(n)
        reg.counter(
            f"bulk_{token.source}_slots_total",
            help=("bulk samples run in residual bucket padding"
                  if token.source == "scavenged"
                  else "bulk samples run in idle flush windows"),
        ).inc(n)
        reg.counter("bulk_parts_written_total",
                    help="sink part files durably written").inc()
        self._gauge_backlog()

    def abandon(self, token: _FillToken) -> None:
        """Rewind a staged chunk (failed batch, shutdown mid-flight):
        the slots return to the pool and will re-execute — exactly-once
        is preserved because nothing was committed."""
        with self._lock:
            job = self._jobs.get(token.job)
            if job is None:
                return
            shard = job.shards.get(token.shard_lo)
            if shard is not None and shard["busy"]:
                shard["staged"] = token.lo
                shard["busy"] = False

    # -- idle-window execution ----------------------------------------------
    def run_idle_once(self) -> int:
        """Execute ONE pure-bulk bucket if (and only if) no online work
        is queued for the job's endpoint — the instant-preemption gate:
        a bulk batch never starts while an online image waits.  Returns
        slots executed."""
        with self._lock:
            candidates = [(name, job.spec)
                          for name, job in self._jobs.items()
                          if not job.paused]
        for name, spec in candidates:
            engine = self.engine
            endpoint = spec.transform
            batcher = engine.batchers.get(endpoint)
            if batcher is not None:
                if batcher.depth > 0:
                    continue  # online admission preempts before we start
            elif any(b.depth > 0 for b in engine.batchers.values()):
                # offline-only transforms ("index") have no batcher of
                # their own; ANY queued online image preempts them — the
                # scavenger never competes with admitted work
                continue
            try:
                params, caches, _ = self._resolve_version(spec)
            except ValueError:
                continue  # pin evicted since activation: job waits
            cache = caches[endpoint]
            token = self.fill(endpoint, cache.max_bucket, source="idle",
                              job_name=name)
            if token is None:
                continue
            try:
                out = np.asarray(cache(params, token.imgs))
            except Exception:
                self.abandon(token)
                self.registry.counter(
                    "bulk_batch_errors_total",
                    help="bulk bucket executions that raised "
                         "(slots were rewound, never dropped)",
                ).inc()
                raise
            self.poll_compiles(cache)
            self.complete(token, out)
            return token.hi - token.lo
        return 0

    def poll_compiles(self, cache) -> None:
        """Bulk rides warmed executables only: fold any compile into the
        shared request-path budget so the zero-after-warmup acceptance
        covers the scavenger too."""
        new_compiles = cache.poll_compiles()
        if new_compiles:
            self.registry.counter(
                "serving_xla_compiles",
                help="request-path XLA compiles after warmup (must stay 0)",
            ).inc(new_compiles)

    def _idle_loop(self) -> None:
        while not self._stop.wait(self.idle_poll_s):
            try:
                if self.run_idle_once() == 0:
                    continue
            except Exception:  # glomlint: disable=conc-broad-except -- counted in run_idle_once; a bad batch must not kill the scavenger thread (the slots were rewound)
                continue

    # -- views ---------------------------------------------------------------
    def backlog(self) -> int:
        with self._lock:
            return sum(job.remaining for job in self._jobs.values())

    def _gauge_backlog(self) -> None:
        with self._lock:
            backlog = sum(job.remaining for job in self._jobs.values())
            active = len(self._jobs)
        self.registry.gauge(
            "bulk_backlog_slots",
            help="bulk slots queued but not yet durably finished",
        ).set(backlog)
        self.registry.gauge(
            "bulk_jobs_active", help="bulk jobs pending/running locally",
        ).set(active)

    def rate_slots_per_s(self) -> Optional[float]:
        with self._lock:
            if len(self._progress) < 2:
                return None
            (t0, n0), (t1, n1) = self._progress[0], self._progress[-1]
        if t1 <= t0:
            return None
        return (n1 - n0) / (t1 - t0)

    def summary(self) -> Dict[str, Any]:
        """The ``/healthz`` ``bulk`` block (and ``/admin/jobs/status``
        with no name): store summary + live rate/ETA.  The router's
        health loop ingests this — including per-shard cursors, which is
        what lets it re-partition a DEAD replica's range from its last
        witnessed durable cursor."""
        doc = self.store.summary()
        rate = self.rate_slots_per_s()
        doc["rate_slots_per_s"] = None if rate is None else round(rate, 3)
        doc["eta_s"] = (round(doc["backlog"] / rate, 3)
                        if rate and doc["backlog"] else None)
        with self._lock:
            doc["slots_done_session"] = self._slots_done
        return doc

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._idle_loop, name="glom-bulk-idle", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
