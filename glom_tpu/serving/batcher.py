"""Deadline-aware dynamic micro-batcher with admission control.

The request path's first stage: callers :meth:`~DynamicBatcher.submit`
payloads (image arrays) and get a ``concurrent.futures.Future``; a worker
thread pulls flushed batches with :meth:`~DynamicBatcher.next_batch` and
resolves the futures.  Two flush rules, whichever fires first:

  * **size** — the queued image count reaches ``max_batch`` (a full device
    batch is waiting; adding latency buys nothing);
  * **deadline** — the OLDEST queued item has waited ``max_wait_ms`` (the
    batching gain is bounded, the latency cost is not — flush partial).

Admission control is load shedding, not unbounded queueing: when the
queue already holds ``max_queue`` images, ``submit`` raises
:class:`Overloaded` immediately and the server turns it into a structured
503 — a client that can see "overloaded" can back off; a client stuck
behind an unbounded queue just times out and retries, making the overload
worse (the PAPERS.md serving lesson: shed early, never queue unboundedly).

**Tenant bulkheads**: :class:`TenantAdmission` holds one token bucket per
configured tenant (``rate[:burst]`` in images/s), shared across every
endpoint batcher of an engine.  A tenant past its quota sheds with
:class:`TenantQuotaExceeded` — a 503 the CLIENT can attribute to its own
budget — while other tenants' admission, queueing, and latency are
untouched: the quota keeps any one tenant from filling the shared queue,
which is the isolation the per-tenant SLOs (:mod:`glom_tpu.obs.slo`)
promise.

Time is injectable (``clock``) and the flush decision is a pure function
of queue state + clock (:meth:`next_batch` with ``block=False`` never
sleeps), so every semantics test runs deterministically with a fake clock
— no real sleeps, no flaky timing.  The blocking form used by the real
worker thread layers a condition-variable wait on top of the same
decision.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, List, Optional


class Overloaded(RuntimeError):
    """Queue at capacity: the request was shed, not enqueued."""


class TenantQuotaExceeded(Overloaded):
    """One tenant's token bucket is empty: only THAT tenant's request was
    shed — the bulkhead contract (a saturating tenant never consumes the
    shared queue's headroom)."""

    def __init__(self, message: str, tenant: str):
        super().__init__(message)
        self.tenant = tenant


class Closed(RuntimeError):
    """Submitted after shutdown began: the request was not enqueued."""


class TokenBucket:
    """Classic token bucket over an injectable clock: ``rate`` tokens/s
    refill up to ``burst`` capacity; :meth:`take` consumes atomically or
    not at all.  NOT internally locked — the owner
    (:class:`TenantAdmission`) serializes access."""

    def __init__(self, rate: float, burst: float, *, clock=None):
        if rate <= 0:
            raise ValueError(f"rate must be > 0 tokens/s, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1 token, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock if clock is not None else time.monotonic
        self._tokens = self.burst  # a fresh tenant starts with full burst
        self._last = self._clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens


def parse_quota(spec) -> "tuple":
    """``"RATE"`` or ``"RATE:BURST"`` (images/s; burst defaults to
    ``max(rate, 1)``) -> ``(rate, burst)``.  Tuples/lists pass through."""
    if isinstance(spec, (tuple, list)):
        rate, burst = float(spec[0]), float(spec[1])
        return rate, burst
    text = str(spec)
    if ":" in text:
        rate_s, burst_s = text.split(":", 1)
        return float(rate_s), float(burst_s)
    rate = float(text)
    return rate, max(rate, 1.0)


class TenantAdmission:
    """Per-tenant token-bucket bulkheads, shared across every endpoint
    batcher of one engine (a quota is a promise about the TENANT's load,
    not about one endpoint's).

    ``quotas`` maps tenant name -> quota spec (:func:`parse_quota`);
    tenants without a configured quota are unlimited here and bounded
    only by the global ``max_queue``.  :meth:`admit` consumes
    ``images`` tokens or raises :class:`TenantQuotaExceeded` — the shed
    is charged to the tenant (tokens are only consumed on admission, so
    a storm of rejected requests cannot starve the tenant's own future
    budget).  Injectable clock; internally locked (handler threads race
    through admission)."""

    def __init__(self, quotas: dict, *, clock=None):
        clock = clock if clock is not None else time.monotonic
        self._buckets = {}
        self._limits = {}
        for tenant, spec in (quotas or {}).items():
            rate, burst = parse_quota(spec)
            self._buckets[tenant] = TokenBucket(rate, burst, clock=clock)
            self._limits[tenant] = (rate, burst)
        self._lock = threading.Lock()
        self.admitted: dict = {t: 0 for t in self._buckets}
        self.shed: dict = {t: 0 for t in self._buckets}

    def admit(self, tenant: Optional[str], images: int) -> None:
        if tenant is None:
            return
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                return
            if bucket.take(images):
                self.admitted[tenant] += images
                return
            self.shed[tenant] += 1
        raise TenantQuotaExceeded(
            f"tenant {tenant!r} over its admission quota "
            f"({self._limits[tenant][0]:g} imgs/s, "
            f"burst {self._limits[tenant][1]:g}); request shed",
            tenant,
        )

    def refund(self, tenant: Optional[str], images: int) -> None:
        """Return tokens consumed for a request that was then rejected
        DOWNSTREAM (global queue shed): the tenant's budget must reflect
        work actually admitted, or a fleet-wide overload would burn
        every tenant's quota for requests nobody served."""
        if tenant is None:
            return
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is not None:
                bucket._tokens = min(bucket.burst, bucket._tokens + images)
                self.admitted[tenant] = max(0, self.admitted[tenant] - images)

    def snapshot(self) -> dict:
        """Per-tenant quota state for ``/healthz``."""
        with self._lock:
            return {
                tenant: {
                    "rate": self._limits[tenant][0],
                    "burst": self._limits[tenant][1],
                    "tokens": round(self._buckets[tenant].tokens, 3),
                    "admitted_images": self.admitted[tenant],
                    "shed_requests": self.shed[tenant],
                }
                for tenant in sorted(self._buckets)
            }


@dataclass
class _Item:
    payload: Any
    size: int
    enqueued_at: float
    future: Future = field(default_factory=Future)
    # -- tracing (glom_tpu.obs.tracing) --
    ctx: Any = None          # the request's span context (root span)
    queue_span: Any = None   # open queue_wait span, closed at batch take
    batch_span: Any = None   # the batch-level span this item flushed into
    # -- multi-tenant / multi-version routing (engine.process_once) --
    tenant: Optional[str] = None
    # (model, step) the item must execute against; None = the default
    # model's primary params.  Items with different keys share a flush
    # but execute as separate groups (one params tree per dispatch).
    mkey: Any = None


class BatcherStats:
    """Host-side counters the engine mirrors into its metric registry."""

    def __init__(self):
        self.submitted = 0       # accepted submissions (items, not images)
        self.shed = 0            # rejected-at-capacity submissions
        self.flush_full = 0      # batches flushed by the size rule
        self.flush_deadline = 0  # batches flushed by the deadline rule
        self.flush_drain = 0     # batches flushed by shutdown drain


class DynamicBatcher:
    """Bounded queue + the two flush rules; see module docstring.

    ``max_batch``/``max_queue`` count IMAGES (an item may carry several),
    so a device-batch budget holds regardless of how clients group their
    requests.  An item larger than ``max_batch`` can never flush and is
    rejected at submit (ValueError — caller bug, not load)."""

    def __init__(self, *, max_batch: int = 8, max_wait_ms: float = 5.0,
                 max_queue: int = 64, clock=None, tracer=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue < max_batch:
            raise ValueError(
                f"max_queue ({max_queue}) must be >= max_batch "
                f"({max_batch}) or a full batch could never queue"
            )
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.max_queue = max_queue
        self._clock = clock if clock is not None else time.monotonic
        self._tracer = tracer
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._queued = 0          # images currently queued
        self._closed = False
        self._draining = False
        self.stats = BatcherStats()

    # -- admission ---------------------------------------------------------
    @property
    def depth(self) -> int:
        """Queued image count (the queue-depth gauge's source)."""
        with self._cond:
            return self._queued

    def submit(self, payload: Any, size: int = 1, *, ctx=None,
               tenant: Optional[str] = None, mkey: Any = None) -> Future:
        """Enqueue ``payload`` (``size`` images); returns the Future the
        worker resolves.  Raises :class:`Overloaded` at capacity (shed) or
        :class:`Closed` after shutdown began.  ``ctx`` (a span context
        from :mod:`glom_tpu.obs.tracing`) opens a ``queue_wait`` span
        under the request's trace, closed when the batch is taken.
        ``tenant`` labels the item (quota admission happens upstream in
        the engine, against the shared :class:`TenantAdmission`);
        ``mkey`` pins the item to a (model, step) params tree."""
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        if size > self.max_batch:
            raise ValueError(
                f"item of {size} images exceeds max_batch {self.max_batch}; "
                f"split the request client-side"
            )
        with self._cond:
            if self._closed:
                raise Closed("batcher is shut down")
            if self._queued + size > self.max_queue:
                self.stats.shed += 1
                raise Overloaded(
                    f"queue at capacity ({self._queued}/{self.max_queue} "
                    f"images); request shed"
                )
            item = _Item(payload=payload, size=size,
                         enqueued_at=self._clock(), ctx=ctx,
                         tenant=tenant, mkey=mkey)
            if self._tracer is not None and ctx is not None:
                from glom_tpu.obs.tracing import SPAN_QUEUE_WAIT

                item.queue_span = self._tracer.start_span(
                    SPAN_QUEUE_WAIT, ctx, attrs={"images": size},
                )
            self._queue.append(item)
            self._queued += size
            self.stats.submitted += 1
            self._cond.notify_all()
            return item.future

    # -- flush decision ----------------------------------------------------
    def _flush_reason(self, now: float) -> Optional[str]:
        """Why the head of the queue should flush NOW, or None.  Caller
        holds the lock."""
        if not self._queue:
            return None
        if self._queued >= self.max_batch:
            return "full"
        if self._draining:
            return "drain"
        if now - self._queue[0].enqueued_at >= self.max_wait_s:
            return "deadline"
        return None

    def _take_batch(self, reason: str) -> List[_Item]:
        """Pop items from the head until the next item would overflow
        ``max_batch``.  Caller holds the lock."""
        batch: List[_Item] = []
        total = 0
        while self._queue and total + self._queue[0].size <= self.max_batch:
            item = self._queue.popleft()
            total += item.size
            batch.append(item)
        self._queued -= total
        counter = {"full": "flush_full", "deadline": "flush_deadline",
                   "drain": "flush_drain"}[reason]
        setattr(self.stats, counter, getattr(self.stats, counter) + 1)
        if self._tracer is not None and any(
            it.queue_span is not None for it in batch
        ):
            from glom_tpu.obs.tracing import SPAN_BATCH

            # one batch-level span (its own trace) LINKS the member
            # request spans — a multi-parent span doesn't exist, links do
            batch_span = self._tracer.start_trace(SPAN_BATCH, attrs={
                "flush_reason": reason,
                "items": len(batch),
                "images": total,
                "links": [f"{it.ctx.trace_id}:{it.ctx.span_id}"
                          for it in batch if it.ctx is not None],
            })
            for it in batch:
                it.batch_span = batch_span
                if it.queue_span is not None:
                    self._tracer.end(it.queue_span,
                                     attrs={"flush_reason": reason})
        return batch

    def next_batch(self, *, block: bool = True,
                   timeout: Optional[float] = None) -> Optional[List[_Item]]:
        """The worker's pull: a non-empty list of items when a flush rule
        fired, or None.

        ``block=False`` (the deterministic test form) evaluates the flush
        rules against the injected clock and returns immediately.
        ``block=True`` waits on the condition variable until a rule fires,
        shutdown drains the queue dry (returns None — the worker exits), or
        ``timeout`` elapses."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                reason = self._flush_reason(self._clock())
                if reason is not None:
                    return self._take_batch(reason)
                if self._closed and not self._queue:
                    return None  # drained dry: worker exits
                if not block:
                    return None
                # wait until: new submission, shutdown, or the head item's
                # deadline — whichever is nearest.  An EMPTY queue has no
                # deadline to honor, so it waits on the condition alone
                # (a timed wait there would busy-poll at max_wait_ms=0)
                wait = None
                if self._queue:
                    wait = max(
                        0.0,
                        self._queue[0].enqueued_at + self.max_wait_s
                        - self._clock(),
                    )
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(
                    timeout=None if wait is None else max(wait, 1e-4))

    # -- shutdown ----------------------------------------------------------
    def close(self, *, drain: bool = True) -> None:
        """Stop admitting.  ``drain=True`` (graceful): queued items keep
        flushing (ignoring the deadline — there is no later batch to merge
        with) until the queue is dry, then ``next_batch`` returns None.
        ``drain=False`` (abort): pending futures fail with
        :class:`Closed` so no client hangs on a result that will never
        come.  Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if drain:
                self._draining = True
            else:
                for item in self._queue:
                    item.future.set_exception(Closed("batcher shut down"))
                self._queue.clear()
                self._queued = 0
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
