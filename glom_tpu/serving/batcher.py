"""Deadline-aware dynamic micro-batcher with admission control.

The request path's first stage: callers :meth:`~DynamicBatcher.submit`
payloads (image arrays) and get a ``concurrent.futures.Future``; a worker
thread pulls flushed batches with :meth:`~DynamicBatcher.next_batch` and
resolves the futures.  Two flush rules, whichever fires first:

  * **size** — the queued image count reaches ``max_batch`` (a full device
    batch is waiting; adding latency buys nothing);
  * **deadline** — the OLDEST queued item has waited ``max_wait_ms`` (the
    batching gain is bounded, the latency cost is not — flush partial).

Admission control is load shedding, not unbounded queueing: when the
queue already holds ``max_queue`` images, ``submit`` raises
:class:`Overloaded` immediately and the server turns it into a structured
503 — a client that can see "overloaded" can back off; a client stuck
behind an unbounded queue just times out and retries, making the overload
worse (the PAPERS.md serving lesson: shed early, never queue unboundedly).

Time is injectable (``clock``) and the flush decision is a pure function
of queue state + clock (:meth:`next_batch` with ``block=False`` never
sleeps), so every semantics test runs deterministically with a fake clock
— no real sleeps, no flaky timing.  The blocking form used by the real
worker thread layers a condition-variable wait on top of the same
decision.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, List, Optional


class Overloaded(RuntimeError):
    """Queue at capacity: the request was shed, not enqueued."""


class Closed(RuntimeError):
    """Submitted after shutdown began: the request was not enqueued."""


@dataclass
class _Item:
    payload: Any
    size: int
    enqueued_at: float
    future: Future = field(default_factory=Future)
    # -- tracing (glom_tpu.obs.tracing) --
    ctx: Any = None          # the request's span context (root span)
    queue_span: Any = None   # open queue_wait span, closed at batch take
    batch_span: Any = None   # the batch-level span this item flushed into


class BatcherStats:
    """Host-side counters the engine mirrors into its metric registry."""

    def __init__(self):
        self.submitted = 0       # accepted submissions (items, not images)
        self.shed = 0            # rejected-at-capacity submissions
        self.flush_full = 0      # batches flushed by the size rule
        self.flush_deadline = 0  # batches flushed by the deadline rule
        self.flush_drain = 0     # batches flushed by shutdown drain


class DynamicBatcher:
    """Bounded queue + the two flush rules; see module docstring.

    ``max_batch``/``max_queue`` count IMAGES (an item may carry several),
    so a device-batch budget holds regardless of how clients group their
    requests.  An item larger than ``max_batch`` can never flush and is
    rejected at submit (ValueError — caller bug, not load)."""

    def __init__(self, *, max_batch: int = 8, max_wait_ms: float = 5.0,
                 max_queue: int = 64, clock=None, tracer=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue < max_batch:
            raise ValueError(
                f"max_queue ({max_queue}) must be >= max_batch "
                f"({max_batch}) or a full batch could never queue"
            )
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.max_queue = max_queue
        self._clock = clock if clock is not None else time.monotonic
        self._tracer = tracer
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._queued = 0          # images currently queued
        self._closed = False
        self._draining = False
        self.stats = BatcherStats()

    # -- admission ---------------------------------------------------------
    @property
    def depth(self) -> int:
        """Queued image count (the queue-depth gauge's source)."""
        with self._cond:
            return self._queued

    def submit(self, payload: Any, size: int = 1, *, ctx=None) -> Future:
        """Enqueue ``payload`` (``size`` images); returns the Future the
        worker resolves.  Raises :class:`Overloaded` at capacity (shed) or
        :class:`Closed` after shutdown began.  ``ctx`` (a span context
        from :mod:`glom_tpu.obs.tracing`) opens a ``queue_wait`` span
        under the request's trace, closed when the batch is taken."""
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        if size > self.max_batch:
            raise ValueError(
                f"item of {size} images exceeds max_batch {self.max_batch}; "
                f"split the request client-side"
            )
        with self._cond:
            if self._closed:
                raise Closed("batcher is shut down")
            if self._queued + size > self.max_queue:
                self.stats.shed += 1
                raise Overloaded(
                    f"queue at capacity ({self._queued}/{self.max_queue} "
                    f"images); request shed"
                )
            item = _Item(payload=payload, size=size,
                         enqueued_at=self._clock(), ctx=ctx)
            if self._tracer is not None and ctx is not None:
                from glom_tpu.obs.tracing import SPAN_QUEUE_WAIT

                item.queue_span = self._tracer.start_span(
                    SPAN_QUEUE_WAIT, ctx, attrs={"images": size},
                )
            self._queue.append(item)
            self._queued += size
            self.stats.submitted += 1
            self._cond.notify_all()
            return item.future

    # -- flush decision ----------------------------------------------------
    def _flush_reason(self, now: float) -> Optional[str]:
        """Why the head of the queue should flush NOW, or None.  Caller
        holds the lock."""
        if not self._queue:
            return None
        if self._queued >= self.max_batch:
            return "full"
        if self._draining:
            return "drain"
        if now - self._queue[0].enqueued_at >= self.max_wait_s:
            return "deadline"
        return None

    def _take_batch(self, reason: str) -> List[_Item]:
        """Pop items from the head until the next item would overflow
        ``max_batch``.  Caller holds the lock."""
        batch: List[_Item] = []
        total = 0
        while self._queue and total + self._queue[0].size <= self.max_batch:
            item = self._queue.popleft()
            total += item.size
            batch.append(item)
        self._queued -= total
        counter = {"full": "flush_full", "deadline": "flush_deadline",
                   "drain": "flush_drain"}[reason]
        setattr(self.stats, counter, getattr(self.stats, counter) + 1)
        if self._tracer is not None and any(
            it.queue_span is not None for it in batch
        ):
            from glom_tpu.obs.tracing import SPAN_BATCH

            # one batch-level span (its own trace) LINKS the member
            # request spans — a multi-parent span doesn't exist, links do
            batch_span = self._tracer.start_trace(SPAN_BATCH, attrs={
                "flush_reason": reason,
                "items": len(batch),
                "images": total,
                "links": [f"{it.ctx.trace_id}:{it.ctx.span_id}"
                          for it in batch if it.ctx is not None],
            })
            for it in batch:
                it.batch_span = batch_span
                if it.queue_span is not None:
                    self._tracer.end(it.queue_span,
                                     attrs={"flush_reason": reason})
        return batch

    def next_batch(self, *, block: bool = True,
                   timeout: Optional[float] = None) -> Optional[List[_Item]]:
        """The worker's pull: a non-empty list of items when a flush rule
        fired, or None.

        ``block=False`` (the deterministic test form) evaluates the flush
        rules against the injected clock and returns immediately.
        ``block=True`` waits on the condition variable until a rule fires,
        shutdown drains the queue dry (returns None — the worker exits), or
        ``timeout`` elapses."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                reason = self._flush_reason(self._clock())
                if reason is not None:
                    return self._take_batch(reason)
                if self._closed and not self._queue:
                    return None  # drained dry: worker exits
                if not block:
                    return None
                # wait until: new submission, shutdown, or the head item's
                # deadline — whichever is nearest.  An EMPTY queue has no
                # deadline to honor, so it waits on the condition alone
                # (a timed wait there would busy-poll at max_wait_ms=0)
                wait = None
                if self._queue:
                    wait = max(
                        0.0,
                        self._queue[0].enqueued_at + self.max_wait_s
                        - self._clock(),
                    )
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(
                    timeout=None if wait is None else max(wait, 1e-4))

    # -- shutdown ----------------------------------------------------------
    def close(self, *, drain: bool = True) -> None:
        """Stop admitting.  ``drain=True`` (graceful): queued items keep
        flushing (ignoring the deadline — there is no later batch to merge
        with) until the queue is dry, then ``next_batch`` returns None.
        ``drain=False`` (abort): pending futures fail with
        :class:`Closed` so no client hangs on a result that will never
        come.  Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if drain:
                self._draining = True
            else:
                for item in self._queue:
                    item.future.set_exception(Closed("batcher shut down"))
                self._queue.clear()
                self._queued = 0
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
