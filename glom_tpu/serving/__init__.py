"""Online serving subsystem: the inference half of the north star.

Turns a trained checkpoint into a service — the first consumer of the
model outside the trainer, and the first subsystem exercising the
telemetry/forensics stack (PRs 1-2) on the request path:

  * :mod:`glom_tpu.serving.batcher` — bounded request queue, deadline-
    aware dynamic micro-batching (flush on ``max_batch`` or
    ``max_wait_ms``), load-shedding admission control;
  * :mod:`glom_tpu.serving.compile_cache` — shape-bucketed padded
    batching with ahead-of-time compilation of every bucket at startup
    (``jax.jit(...).lower(...).compile()``), so the request path never
    triggers an XLA compile;
  * :mod:`glom_tpu.serving.engine` — model lifecycle: load from the
    newest finalized checkpoint, hot-reload watcher that atomically swaps
    params when a newer one lands, graceful drain on shutdown, and the
    ``queue_saturation`` forensics trigger;
  * :mod:`glom_tpu.serving.server` — stdlib ``ThreadingHTTPServer``
    front: ``/embed``, ``/reconstruct``, ``/healthz``, ``/metrics``, plus
    the ``/admin/reload/*`` staged-swap API the fleet router drives;
  * :mod:`glom_tpu.serving.sessions` — per-session column-state cache
    for the stateful (video/streaming) workload: TTL + LRU eviction,
    byte-bounded, spill/restore through the checkpoint npz format; the
    state behind ``/session/embed``'s warm-started frames;
  * :mod:`glom_tpu.serving.sharded` — mesh-sharded serving: buckets
    AOT-compile against explicit in/out shardings so TP/EP-sharded
    configs serve from the ``parallel/`` stack with zero request-path
    compiles;
  * :mod:`glom_tpu.serving.router` — the fleet tier: one front door over
    N engine replicas (least-loaded + consistent-hash dispatch,
    health-aware ejection/re-admission, aggregated per-replica metrics,
    trace propagation through the hop, coordinated two-phase hot-reload);
  * :mod:`glom_tpu.serving.registry` — the multi-tenant model registry:
    named models/versions resident at once, per-version compile-cache
    namespaces with AOT aliasing, checkpoint lineage anchored on
    ``integrity.latest_valid_step``;
  * :mod:`glom_tpu.serving.deploy` — the safe-deploy state machine:
    shadow (mirrored, discarded, candidate-only accounting) -> canary
    (deterministic affinity-hashed fraction) -> burn-rate auto-promote /
    auto-rollback with a ``deploy_rollback`` forensics bundle; tenant
    bulkheads (token-bucket admission, per-tenant SLOs/metrics) ride
    :mod:`glom_tpu.serving.batcher`'s :class:`TenantAdmission`.

``tools/loadgen.py`` drives it (closed/open loop, p50/p95/p99 report,
multi-target + per-replica breakdown); ``docs/SERVING.md`` documents
tuning.  Quickstart::

    python -m glom_tpu.serving.server --checkpoint-dir /ckpt --port 8000
    python -m glom_tpu.serving.router --spawn 4 --checkpoint-dir /ckpt
"""

from glom_tpu.serving.batcher import (  # noqa: F401
    Closed,
    DynamicBatcher,
    Overloaded,
    TenantAdmission,
    TenantQuotaExceeded,
    TokenBucket,
)
from glom_tpu.serving.registry import (  # noqa: F401
    ModelRegistry,
    ModelVersion,
)
from glom_tpu.serving.compile_cache import (  # noqa: F401
    BucketedCompileCache,
    pad_to_bucket,
    pick_bucket,
)
from glom_tpu.serving.engine import (  # noqa: F401
    ServingEngine,
    make_demo_checkpoint,
)
from glom_tpu.serving.router import (  # noqa: F401
    FleetRouter,
    NoHealthyReplica,
)
from glom_tpu.serving.sessions import (  # noqa: F401
    SessionStore,
    valid_session_id,
)

# glom_tpu.serving.server is intentionally NOT imported here: the package
# runs as `python -m glom_tpu.serving.server`, and importing the submodule
# from its own package __init__ would make runpy warn about re-execution.
