"""Online serving subsystem: the inference half of the north star.

Turns a trained checkpoint into a service — the first consumer of the
model outside the trainer, and the first subsystem exercising the
telemetry/forensics stack (PRs 1-2) on the request path:

  * :mod:`glom_tpu.serving.batcher` — bounded request queue, deadline-
    aware dynamic micro-batching (flush on ``max_batch`` or
    ``max_wait_ms``), load-shedding admission control;
  * :mod:`glom_tpu.serving.compile_cache` — shape-bucketed padded
    batching with ahead-of-time compilation of every bucket at startup
    (``jax.jit(...).lower(...).compile()``), so the request path never
    triggers an XLA compile;
  * :mod:`glom_tpu.serving.engine` — model lifecycle: load from the
    newest finalized checkpoint, hot-reload watcher that atomically swaps
    params when a newer one lands, graceful drain on shutdown, and the
    ``queue_saturation`` forensics trigger;
  * :mod:`glom_tpu.serving.server` — stdlib ``ThreadingHTTPServer``
    front: ``/embed``, ``/reconstruct``, ``/healthz``, ``/metrics``.

``tools/loadgen.py`` drives it (closed/open loop, p50/p95/p99 report);
``docs/SERVING.md`` documents tuning.  Quickstart::

    python -m glom_tpu.serving.server --checkpoint-dir /ckpt --port 8000
"""

from glom_tpu.serving.batcher import (  # noqa: F401
    Closed,
    DynamicBatcher,
    Overloaded,
)
from glom_tpu.serving.compile_cache import (  # noqa: F401
    BucketedCompileCache,
    pad_to_bucket,
    pick_bucket,
)
from glom_tpu.serving.engine import (  # noqa: F401
    ServingEngine,
    make_demo_checkpoint,
)

# glom_tpu.serving.server is intentionally NOT imported here: the package
# runs as `python -m glom_tpu.serving.server`, and importing the submodule
# from its own package __init__ would make runpy warn about re-execution.
