"""Per-session column-state cache for stateful (video/streaming) serving.

GLOM's stateful recipe carries the column ``levels`` state across
consecutive frames (``models/video.py``; PAPER.md §layer map).  Serving
that recipe means the state must live SOMEWHERE between two HTTP
requests — this module is that somewhere:

  * one :class:`SessionEntry` per client session, holding the session's
    settled ``(bucket, n, L, d)`` levels **on device** (the whole point
    of O(1) incremental serving is that the state never crosses the
    host/device boundary between frames — arXiv:2603.09555's fixed-size
    carried state, GLOM-shaped);
  * the state is stored at its compile-cache **bucket** batch size, not
    the request's real batch: the next frame feeds it straight back into
    the bucket's AOT executable with zero padding/reshaping work (a
    per-frame device pad would be a new shape — a request-path compile);
  * **TTL + LRU eviction, size-bounded in bytes**: abandoned streams age
    out on ``ttl_s``, and when the resident set exceeds ``max_bytes``
    the least-recently-used sessions are dropped (the newest entry is
    always retained, so an over-budget single session degrades to
    cold-per-frame rather than erroring);
  * **per-session locks**: frame k+1 depends on frame k, so two racing
    requests for one session serialize; distinct sessions never contend;
  * optional **spill/restore** in the checkpoint npz format
    (``sessions.npz`` + ``sessions.json`` manifest, atomic tmp+rename
    writes) so a drained replica's warm state survives a process
    restart — the fleet reloads warm instead of paying every client a
    cold re-settle.

Everything is observable through the shared registry:
``serving_session_count`` / ``serving_session_bytes`` gauges plus
hit/miss/eviction/reset/spill counters (``serving_session_*``).

The store is deliberately ignorant of jax beyond ``device_put``/
``device_get`` at the spill boundary: entries hold whatever array object
the engine gives them.  All clocks are injectable (tests drive TTL
deterministically); ``time.monotonic`` is the default.
"""

from __future__ import annotations

import contextlib
import json
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np

# the session-id contract, enforced at the HTTP boundary and re-checked
# here (ids become npz keys and affinity-hash inputs; a hostile id must
# not be able to traverse paths or splice the spill manifest)
SESSION_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")

SPILL_NPZ = "sessions.npz"
SPILL_MANIFEST = "sessions.json"
_SPILL_FORMAT = 1


def valid_session_id(session_id: str) -> bool:
    return isinstance(session_id, str) and bool(SESSION_ID_RE.match(session_id))


@dataclass
class SessionEntry:
    """One session's carried state.  ``levels`` is bucket-shaped (the AOT
    executable's aval), ``batch`` is the session's real per-frame image
    count — embeddings are sliced to it host-side, the state never is."""

    levels: Any                 # (bucket, n, L, d) device array
    batch: int                  # real images per frame for this session
    bucket: int                 # compile-cache bucket the state is shaped for
    step: int                   # checkpoint step at the last update
    frames: int = 0             # frames processed so far
    last_used: float = 0.0      # store-clock timestamp of the last touch
    nbytes: int = 0

    def meta(self) -> dict:
        return {"batch": int(self.batch), "bucket": int(self.bucket),
                "step": int(self.step), "frames": int(self.frames)}


@dataclass
class SessionStats:
    hits: int = 0
    misses: int = 0
    resets: int = 0
    evicted_ttl: int = 0
    evicted_lru: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)


def _leaf_nbytes(levels) -> int:
    nbytes = getattr(levels, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return int(np.asarray(levels).nbytes)


class SessionStore:
    """TTL + LRU, byte-bounded map ``session_id -> SessionEntry``.

    The map lock covers only dict bookkeeping (O(1) per op); per-session
    locks (:meth:`lock`) are held by the engine across a frame's whole
    get-execute-put so one session's frames serialize while the device
    pipelines other sessions' work.
    """

    def __init__(self, *, max_bytes: int = 256 * 2 ** 20,
                 ttl_s: float = 600.0, registry=None, clock=None):
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.registry = registry
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, SessionEntry]" = OrderedDict()
        self._locks: Dict[str, threading.Lock] = {}
        self._bytes = 0
        self._last_sweep = self._clock()
        self.stats = SessionStats()

    # -- registry plumbing -------------------------------------------------
    def _counter(self, name: str, help: str):
        if self.registry is not None:
            self.registry.counter(name, help=help).inc()

    def _export_gauges(self) -> None:
        if self.registry is None:
            return
        self.registry.gauge(
            "serving_session_count", help="resident session states",
        ).set(len(self._entries))
        self.registry.gauge(
            "serving_session_bytes",
            help="bytes of resident session state", unit="bytes",
        ).set(self._bytes)

    # -- per-session serialization ----------------------------------------
    def lock(self, session_id: str) -> threading.Lock:
        """The session's frame-ordering lock object.  Callers serializing
        a frame must use :meth:`locked` — a bare ``lock().acquire()``
        races lock cleanup (the object can be dropped and re-minted
        between the fetch and the acquire, leaving two threads holding
        two distinct locks for one session)."""
        with self._lock:
            lock = self._locks.get(session_id)
            if lock is None:
                lock = self._locks[session_id] = threading.Lock()
            return lock

    @contextlib.contextmanager
    def locked(self, session_id: str):
        """Hold the session's frame-ordering lock for one frame's whole
        get-execute-put.  Acquisition re-validates that the acquired
        object is STILL the session's mapped lock (an eviction's
        idle-lock cleanup may have dropped and re-minted it in the
        fetch→acquire window) — once validated it cannot be dropped out
        from under us, because cleanup skips held locks."""
        while True:
            lock = self.lock(session_id)
            lock.acquire()  # glomlint: disable=res-leak-on-raise -- the only statement between acquire and the try/finally is the identity re-validation dict probe under self._lock; wrapping it would have to release-before-validate, re-opening the re-mint race this loop exists to close
            with self._lock:
                if self._locks.get(session_id) is lock:
                    break
            lock.release()
        try:
            yield
        finally:
            lock.release()

    def _drop_lock_if_idle(self, session_id: str) -> None:
        # caller holds self._lock; never drop a lock a frame is holding
        lock = self._locks.get(session_id)
        if lock is not None and not lock.locked():
            del self._locks[session_id]

    # -- core map ops ------------------------------------------------------
    def get(self, session_id: str) -> Optional[SessionEntry]:
        """TTL-checked lookup; a hit refreshes both recency orders."""
        now = self._clock()
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                with self.stats._lock:
                    self.stats.misses += 1
                self._counter("serving_session_misses",
                              "session lookups that found no state")
                return None
            if now - entry.last_used > self.ttl_s:
                self._evict_locked(session_id, "ttl")
                self._export_gauges()
                with self.stats._lock:
                    self.stats.misses += 1
                self._counter("serving_session_misses",
                              "session lookups that found no state")
                return None
            entry.last_used = now
            self._entries.move_to_end(session_id)
            with self.stats._lock:
                self.stats.hits += 1
            self._counter("serving_session_hits",
                          "session lookups served from resident state")
            return entry

    def put(self, session_id: str, levels, *, batch: int, bucket: int,
            step: int, frames: int) -> SessionEntry:
        """Insert/replace a session's state, then enforce the byte bound
        (LRU-evicting OTHER sessions; the entry just written always
        stays — see module docstring)."""
        if not valid_session_id(session_id):
            raise ValueError(f"invalid session id {session_id!r}")
        now = self._clock()
        entry = SessionEntry(
            levels=levels, batch=int(batch), bucket=int(bucket),
            step=int(step), frames=int(frames), last_used=now,
            nbytes=_leaf_nbytes(levels),
        )
        with self._lock:
            old = self._entries.pop(session_id, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[session_id] = entry
            self._bytes += entry.nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                oldest = next(iter(self._entries))
                if oldest == session_id:
                    break
                self._evict_locked(oldest, "lru")
            self._export_gauges()
        return entry

    def reset(self, session_id: str) -> bool:
        """Client-requested forget (``/session/reset``)."""
        with self._lock:
            entry = self._entries.pop(session_id, None)
            if entry is None:
                return False
            self._bytes -= entry.nbytes
            self._drop_lock_if_idle(session_id)
            with self.stats._lock:
                self.stats.resets += 1
            self._counter("serving_session_resets",
                          "client-requested session resets")
            self._export_gauges()
            return True

    def _evict_locked(self, session_id: str, why: str) -> None:
        entry = self._entries.pop(session_id)
        self._bytes -= entry.nbytes
        self._drop_lock_if_idle(session_id)
        with self.stats._lock:
            if why == "ttl":
                self.stats.evicted_ttl += 1
            else:
                self.stats.evicted_lru += 1
        self._counter(
            f"serving_session_evictions_{why}",
            "sessions evicted by " + ("TTL expiry" if why == "ttl"
                                      else "LRU byte-bound pressure"),
        )

    def sweep(self, *, min_interval: Optional[float] = None) -> int:
        """Evict every TTL-expired session so abandoned streams don't
        wait for the next byte-pressure event to free their HBM.  Called
        from the engine's reload watcher when one runs, AND interval-
        gated from the session request path itself (``min_interval``
        no-ops the call when a sweep ran recently) — fleet replicas run
        with the watcher disabled (the router owns reloads), so traffic
        must be able to drive TTL reclamation on its own."""
        now = self._clock()
        evicted = 0
        with self._lock:
            if (min_interval is not None
                    and now - self._last_sweep < min_interval):
                return 0
            self._last_sweep = now
            for sid in [sid for sid, e in self._entries.items()
                        if now - e.last_used > self.ttl_s]:
                self._evict_locked(sid, "ttl")
                evicted += 1
            if evicted:
                self._export_gauges()
        return evicted

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def snapshot(self) -> dict:
        """Health/debug payload: counts only, never the state itself."""
        with self._lock:
            count, nbytes = len(self._entries), self._bytes
        with self.stats._lock:
            s = {"hits": self.stats.hits, "misses": self.stats.misses,
                 "resets": self.stats.resets,
                 "evicted_ttl": self.stats.evicted_ttl,
                 "evicted_lru": self.stats.evicted_lru}
        return {"count": count, "bytes": nbytes,
                "max_bytes": self.max_bytes, "ttl_s": self.ttl_s, **s}

    # -- spill / restore (checkpoint npz format) ---------------------------
    def spill(self, directory: str) -> int:
        """Write every resident session to ``directory`` in the checkpoint
        npz layout: one ``sessions.npz`` ('levels/<sid>' keys) plus a
        ``sessions.json`` manifest, both atomic tmp+rename writes (the
        shared :func:`glom_tpu.checkpoint._atomic_write` — a SIGKILL
        mid-spill leaves the previous spill intact, never a torn one).
        Returns the number of sessions written."""
        import os

        import jax

        from glom_tpu import checkpoint as ckpt_lib

        os.makedirs(directory, exist_ok=True)
        with self._lock:
            items = list(self._entries.items())  # oldest -> newest (LRU order)
        arrays = {}
        manifest: Dict[str, dict] = {}
        for sid, entry in items:
            arrays[f"levels/{sid}"] = np.asarray(jax.device_get(entry.levels))
            manifest[sid] = entry.meta()
        payload = json.dumps(
            {"format": _SPILL_FORMAT, "sessions": manifest}, indent=2,
        ).encode()
        ckpt_lib._atomic_write(directory, SPILL_NPZ,
                               lambda f: np.savez(f, **arrays))
        ckpt_lib._atomic_write(directory, SPILL_MANIFEST,
                               lambda f: f.write(payload))
        if self.registry is not None:
            self.registry.counter(
                "serving_session_spills",
                help="session-store spills to the checkpoint npz format",
            ).inc()
        return len(items)

    def restore(self, directory: str, *,
                validate: Optional[Callable[[tuple, Any], bool]] = None,
                place: Optional[Callable[[np.ndarray], Any]] = None) -> int:
        """Reload a spill written by :meth:`spill`.  Missing/torn files
        are a clean no-op (a cold boot is always safe); entries whose
        shape/dtype ``validate(shape, dtype)`` rejects are dropped (the
        model or bucket ladder changed — a cold re-settle is correct,
        stale state silently fed to a new graph is not).  ``place`` maps
        each host array onto the device (the engine's placement rule).
        Ages do not survive a restart (the store clock is monotonic), so
        restored sessions count as freshly used.  Returns sessions
        restored."""
        import os

        npz_path = os.path.join(directory, SPILL_NPZ)
        man_path = os.path.join(directory, SPILL_MANIFEST)
        try:
            with open(man_path) as f:
                manifest = json.load(f)
            data = np.load(npz_path, allow_pickle=False)
        except (OSError, ValueError):
            return 0
        if not isinstance(manifest, dict) or manifest.get("format") != _SPILL_FORMAT:
            return 0
        restored = 0
        try:
            sessions = manifest.get("sessions") or {}
            # iterate in manifest (spill LRU) order: oldest first, so the
            # byte bound applied by put() keeps the NEWEST spilled state
            for sid, meta in sessions.items():
                if not valid_session_id(sid):
                    continue
                key = f"levels/{sid}"
                if key not in getattr(data, "files", []):
                    continue
                levels = data[key]
                if validate is not None and not validate(
                        tuple(levels.shape), levels.dtype):
                    continue
                placed = place(levels) if place is not None else levels
                self.put(sid, placed,
                         batch=int(meta.get("batch", levels.shape[0])),
                         bucket=int(meta.get("bucket", levels.shape[0])),
                         step=int(meta.get("step", 0)),
                         frames=int(meta.get("frames", 0)))
                restored += 1
        finally:
            data.close()
        if restored and self.registry is not None:
            self.registry.counter(
                "serving_session_restores",
                help="sessions restored warm from a spill at startup",
            ).inc(restored)
        return restored
