"""Stdlib-only HTTP front for the serving engine.

``python -m glom_tpu.serving.server --checkpoint-dir /ckpt`` exposes:

  * ``POST /embed`` — ``{"images": [...]}`` (one ``(c,H,W)`` image or a
    ``(k,c,H,W)`` batch as nested lists) -> mean-pooled per-level
    embeddings ``(k, levels, dim)`` (optionally one level via
    ``"level"``);
  * ``POST /reconstruct`` — same request shape -> the denoising forward's
    reconstruction ``(k, c, H, W)``;
  * ``GET /healthz`` — liveness + the model's input contract (loadgen
    reads it to build valid payloads);
  * ``GET /metrics`` — the shared ``glom_tpu.obs`` registry in Prometheus
    exposition format (same families the trainer's textfile exporter
    writes), with OpenMetrics trace-id exemplars on the latency bucket
    lines;
  * ``GET /debug/traces?since=N`` / ``GET /debug/forensics`` — the pull
    plane the fleet observatory (:mod:`glom_tpu.obs.observatory`) polls:
    the tracer's completed-trace ring (incremental by cursor) and this
    replica's forensics bundle manifests + registry snapshot.

``ThreadingHTTPServer`` gives one handler thread per connection; handlers
only parse JSON and park on the engine's future, so the thread count
bounds concurrent WAITERS, not device work — the device sees only the
micro-batched worker.  Overload surfaces as a structured 503
(``{"error": "overloaded"}``) from the batcher's admission control, and
SIGTERM drains in-flight work before exit, mirroring the trainer's
preemption path.

Multi-tenant + safe-deploy plane: requests may carry ``X-Tenant``
(admission rides that tenant's token bucket; outcomes mint per-tenant
metrics and feed per-tenant SLOs) and a ``"model"`` body field (an extra
registry model).  ``POST /admin/deploy/{shadow,canary,promote,rollback,
abort,status}`` drives the shadow/canary lifecycle
(:mod:`glom_tpu.serving.deploy`); ``/healthz`` surfaces the deploy
phase, resident models, and tenant quota state.

Every inference request gets an end-to-end trace
(:mod:`glom_tpu.obs.tracing`): an inbound ``X-Request-Id`` or W3C
``traceparent`` joins the client's trace, a fresh id is minted otherwise,
and the identity is echoed back on every reply (``X-Request-Id`` +
``traceparent`` headers, ``request_id`` in the body).  Error replies
count into ``serving_errors_<class>xx``; request outcomes feed the
engine's SLO burn-rate evaluators (``--slo``).
"""

from __future__ import annotations

import json
import re
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from glom_tpu.obs.exporters import (
    OPENMETRICS_CONTENT_TYPE,
    PROM_TEXT_CONTENT_TYPE,
    prometheus_lines,
    wants_openmetrics,
)
from glom_tpu.obs.tracing import (
    SPAN_DISPATCH_WAIT,
    SPAN_PARSE,
    SPAN_REQUEST,
    SPAN_RESPOND,
    debug_traces_payload,
    format_traceparent,
    parse_traceparent,
    request_trace_id,
)
from glom_tpu.obs.capacity import read_bench_ceiling
from glom_tpu.serving.batcher import Closed, Overloaded, TenantQuotaExceeded
from glom_tpu.serving.engine import ServingEngine

_MAX_BODY = 256 * 1024 * 1024  # refuse absurd payloads before np.asarray
_HEX_ID = re.compile(r"[0-9a-f]{1,32}")
# X-Tenant header charset: label-safe (it is minted into metric names
# through the cardinality-guarded MetricRegistry.labeled)
_TENANT_RE = re.compile(r"[A-Za-z0-9._-]{1,64}")


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True   # handler threads must not block process exit
    allow_reuse_address = True
    # see RouterHTTPServer: the default backlog of 5 drops SYNs under
    # connection bursts (the router opens a fresh upstream connection per
    # proxied request), turning queue pressure into second-scale
    # retransmit stalls
    request_queue_size = 128

    def __init__(self, addr, handler, engine: ServingEngine, *, quiet: bool = True,
                 metrics_timestamps: bool = False):
        super().__init__(addr, handler)
        self.engine = engine
        self.quiet = quiet
        # stamp /metrics samples with unix seconds — OpenMetrics bodies
        # only (the negotiation rule is enforced in prometheus_lines)
        self.metrics_timestamps = metrics_timestamps


class _Handler(BaseHTTPRequestHandler):
    server_version = "glom-serving"
    protocol_version = "HTTP/1.1"
    # headers flush + body write are separate sends; TCP_NODELAY keeps
    # Nagle from parking the body against a delayed ACK (40ms quanta)
    disable_nagle_algorithm = True

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):
        if not getattr(self.server, "quiet", True):
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload, content_type="application/json") -> None:
        if code >= 400:
            # status-class error accounting: the SLO error-rate objective
            # (and any dashboard) needs a real input, including sheds
            self.server.engine.registry.counter(
                f"serving_errors_{code // 100}xx",
                help=f"requests answered with a {code // 100}xx status",
            ).inc()
        body = (json.dumps(payload) if isinstance(payload, (dict, list))
                else payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        # every reply after trace minting echoes the request's identity so
        # a client (or proxy log) can join its traces to ours
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
            tid = self._trace_root.trace_id
            # traceparent requires canonical lowercase hex (int(x, 16)
            # would also accept '-1f'/'0x2a'/'1_2' and emit a malformed
            # header); arbitrary X-Request-Ids still echo above
            if _HEX_ID.fullmatch(tid):
                self.send_header("traceparent", format_traceparent(
                    tid, self._trace_root.span_id))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Optional[dict]:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0 or length > _MAX_BODY:
            self._reply(400, {"error": f"bad Content-Length {length}"})
            return None
        try:
            payload = json.loads(self.rfile.read(length))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self._reply(400, {"error": f"invalid JSON: {e}"})
            return None
        if not isinstance(payload, dict):
            # every route reads fields off the body: a valid-JSON array/
            # scalar must be a clean 400, not an AttributeError mid-handler
            self._reply(400, {"error": "body must be a JSON object"})
            return None
        return payload

    def _tenant(self) -> Optional[str]:
        """The request's tenant (``X-Tenant`` header), or None.  An
        invalid tenant label is replied 400 and reported as the string
        ``""`` sentinel so callers can distinguish "absent" from "bad"."""
        tenant = self.headers.get("X-Tenant")
        if tenant is None:
            return None
        if not _TENANT_RE.fullmatch(tenant):
            self._reply(400, {"error": (
                f"bad X-Tenant {tenant!r}: want 1-64 chars of "
                f"[A-Za-z0-9._-]")})
            return ""
        return tenant

    def _parse_images(self, payload: dict,
                      cfg=None) -> Optional[np.ndarray]:
        if cfg is None:
            cfg = self.server.engine.config
        try:
            imgs = np.asarray(payload["images"], dtype=np.float32)
        except (KeyError, TypeError, ValueError) as e:
            self._reply(400, {"error": f"bad 'images' field: {e}"})
            return None
        if imgs.ndim == 3:
            imgs = imgs[None]
        expected = (cfg.channels, cfg.image_size, cfg.image_size)
        if imgs.ndim != 4 or imgs.shape[1:] != expected or imgs.shape[0] == 0:
            self._reply(400, {"error": (
                f"images must be (k,)+{expected} (or one {expected} image), "
                f"got {tuple(imgs.shape)}"
            )})
            return None
        return imgs

    # -- routes ------------------------------------------------------------
    def do_GET(self):  # noqa: N802 (http.server contract)
        # keep-alive reuses the handler across requests on one connection:
        # a GET must not echo the PREVIOUS request's trace identity
        self._request_id = None
        engine = self.server.engine
        from urllib.parse import urlparse

        parsed = urlparse(self.path)
        if parsed.path == "/healthz":
            self._reply(200, engine.health())
        elif parsed.path == "/metrics":
            # exemplars only under a NEGOTIATED OpenMetrics response: a
            # classic 0.0.4 parser reads the exemplar suffix as a bad
            # timestamp and rejects the ENTIRE scrape.  The OpenMetrics
            # body must end with the spec's `# EOF` terminator or a
            # strict parser rejects it as truncated.
            om = wants_openmetrics(self.headers.get("Accept"))
            # sample timestamps ride the same negotiation as exemplars:
            # OpenMetrics bodies only — 0.0.4 parsers reject them
            body = prometheus_lines(
                engine.registry, exemplars=om,
                timestamps=om and self.server.metrics_timestamps)
            if om:
                body += "# EOF\n"
            self._reply(200, body,
                        content_type=(OPENMETRICS_CONTENT_TYPE if om
                                      else PROM_TEXT_CONTENT_TYPE))
        # -- debug plane: pulled by the fleet observatory ------------------
        # (glom_tpu.obs.observatory).  Read-only, bounded, never on the
        # request path: traces come from the tracer's completed ring,
        # forensics from a directory listing.
        elif parsed.path == "/debug/traces":
            status, payload = debug_traces_payload(
                engine.tracer, parsed.query,
                role="engine", step=int(engine.step))
            self._reply(status, payload)
        elif parsed.path == "/debug/forensics":
            self._reply(200, engine.debug_forensics())
        elif parsed.path == "/debug/series":
            # the TSDB-lite pull plane (glom_tpu.obs.timeseries): ring-
            # bounded history of every registry metric, for trend queries
            self._reply(200, engine.capacity.series_payload(parsed.query))
        elif parsed.path == "/debug/timeline":
            # the engine's unified event timeline (glom_tpu.obs.events):
            # deploy transitions, advisor recommendations, bulk activity —
            # the attribution plane's event-correlation feed
            self._reply(200, {"role": "engine", "step": int(engine.step),
                              "events": engine.timeline.events()})
        elif parsed.path == "/capacity":
            self._reply(200, engine.capacity.payload())
        elif parsed.path == "/quality":
            # the model-quality telemetry plane (glom_tpu.obs.quality):
            # sketch stats, drift vs the reference profile, worst offenders
            self._reply(200, engine.quality.payload())
        elif parsed.path == "/admin/deploy/status":
            self._reply(200, engine.deploy.status())
        elif parsed.path == "/admin/jobs/status":
            # bulk-job progress (read-only GET mirror of the POST verb):
            # ?name= narrows to one job, else the full summary
            if engine.bulk is None:
                self._reply(404, {"error": "bulk tier disabled on this "
                                           "engine (start with --bulk-dir)"})
                return
            from urllib.parse import parse_qs

            q = parse_qs(parsed.query)
            name = q.get("name", [None])[0]
            try:
                self._reply(200, engine.bulk.status(name))
            except KeyError as e:
                self._reply(404, {"error": str(e)})
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    # -- fleet admin: the staged two-phase reload primitive ----------------
    # POSTed by the router's coordinated rollout (docs/SERVING.md fleet
    # section).  Small JSON in/out, no tracing — these are control-plane
    # calls, not requests.
    def _do_admin(self):
        engine = self.server.engine
        action = self.path[len("/admin/reload/"):]
        if action == "prepare":
            payload = self._read_json() if int(
                self.headers.get("Content-Length") or 0) > 0 else {}
            if payload is None:
                return
            step = payload.get("step")
            # glomlint: disable=proto-paired-call -- transport shim: the commit/abort arrive as separate HTTP admin requests; the router's rollout coordinator owns the pairing (and its own lint coverage)
            staged = engine.stage_reload(
                step=int(step) if step is not None else None)
            self._reply(200, {"staged_step": staged,
                              "serving_step": int(engine.step)})
        elif action == "commit":
            step = engine.commit_staged()
            self._reply(200, {"step": step})
        elif action == "abort":
            self._reply(200, {"aborted": engine.abort_staged()})
        elif action == "finalize":
            self._reply(200, {"finalized": engine.finalize_reload()})
        elif action == "rollback":
            step = engine.rollback()
            if step is None:
                self._reply(409, {"error": "nothing to roll back to"})
            else:
                self._reply(200, {"step": step})
        else:
            self._reply(404, {"error": f"no admin action {action!r}"})

    # -- deploy admin: the shadow/canary lifecycle verbs -------------------
    # POSTed by an operator or a fleet deploy driver (docs/SERVING.md
    # deploy section).  Control-plane calls, untraced, mirroring the
    # /admin/reload convention.
    def _do_deploy_admin(self):
        engine = self.server.engine
        deploy = engine.deploy
        action = self.path[len("/admin/deploy/"):]
        payload = (self._read_json() if int(
            self.headers.get("Content-Length") or 0) > 0 else {})
        if payload is None:
            return
        try:
            if action == "shadow":
                step = payload.get("step")
                # glomlint: disable=proto-paired-call -- transport shim: each lifecycle verb arrives as its own HTTP request; the deploy driver owns the pairing (and the controller's auto actions settle a regressing candidate regardless)
                staged = deploy.begin_shadow(
                    step=int(step) if step is not None else None)
                # the /admin/reload/prepare convention: "nothing to
                # deploy" is a clean 200 with a null step, not an error
                self._reply(200, {"candidate_step": staged,
                                  "phase": deploy.phase,
                                  "serving_step": int(engine.step)})
            elif action == "canary":
                step = payload.get("step")
                fraction = payload.get("fraction")
                # glomlint: disable=proto-paired-call -- transport shim (see shadow above)
                staged = deploy.begin_canary(
                    fraction=float(fraction) if fraction is not None
                    else None,
                    step=int(step) if step is not None else None)
                self._reply(200, {"candidate_step": staged,
                                  "phase": deploy.phase,
                                  "serving_step": int(engine.step)})
            elif action == "promote":
                report = deploy.promote()
                self._reply(200 if report is not None else 409,
                            report or {"error": "no active deploy"})
            elif action == "rollback":
                report = deploy.rollback(
                    reason=str(payload.get("reason", "operator")))
                self._reply(200 if report is not None else 409,
                            report or {"error": "no active deploy"})
            elif action == "abort":
                self._reply(200, {"aborted": deploy.abort()})
            elif action == "status":
                self._reply(200, deploy.status())
            else:
                self._reply(404,
                            {"error": f"no deploy action {action!r}"})
        except (RuntimeError, ValueError) as e:
            # a second concurrent deploy, a bad fraction: caller error
            self._reply(409, {"error": str(e)})

    # -- bulk-job admin: the scavenger tier's control verbs ----------------
    # POSTed by tools/bulk_run.py or the router's fleet sharding
    # (docs/BULK.md).  Control-plane calls, untraced, mirroring the
    # /admin/deploy convention.
    def _do_jobs_admin(self):
        engine = self.server.engine
        if engine.bulk is None:
            self._reply(404, {"error": "bulk tier disabled on this engine "
                                       "(start with --bulk-dir)"})
            return
        action = self.path[len("/admin/jobs/"):]
        payload = (self._read_json() if int(
            self.headers.get("Content-Length") or 0) > 0 else {})
        if payload is None:
            return
        try:
            if action == "submit":
                self._reply(200, engine.bulk.submit(payload))
            elif action == "status":
                self._reply(200, engine.bulk.status(payload.get("name")))
            elif action == "pause":
                self._reply(200, engine.bulk.pause(payload["name"]))
            elif action == "resume":
                self._reply(200, engine.bulk.resume(payload["name"]))
            elif action == "cancel":
                self._reply(200, engine.bulk.cancel(payload["name"]))
            else:
                self._reply(404, {"error": f"no jobs action {action!r}"})
        except KeyError as e:
            self._reply(404, {"error": f"unknown job: {e}"})
        except (RuntimeError, ValueError) as e:
            # identity mismatch, overlapping shard, done-job resubmit
            self._reply(409, {"error": str(e)})

    # -- stateful session endpoints ----------------------------------------
    # POST /session/embed: one frame of a stateful stream — warm-starts
    # from the session's resident column state (docs/SERVING.md sessions
    # section).  POST /session/parse: the same frame update, answering
    # with the islanding plus frame-to-frame island deltas
    # (docs/HIERARCHY.md).  POST /session/reset drops the state.  All
    # need the engine constructed with warm_iters=.
    def _do_session(self):
        engine = self.server.engine
        tracer = engine.tracer
        parse = self.path == "/session/parse"
        if not engine.sessions_enabled:
            self._reply(404, {"error": "sessions disabled on this engine "
                                       "(start the server with --warm-iters)"})
            return
        from glom_tpu.serving.sessions import valid_session_id

        if self.path == "/session/reset":
            # control-plane call, untraced (the /admin/reload convention)
            payload = self._read_json()
            if payload is None:
                return
            session_id = payload.get("session")
            if not valid_session_id(session_id):
                self._reply(400, {"error": (
                    f"bad 'session' field {session_id!r}: want 1-128 chars "
                    f"of [A-Za-z0-9._:-]"
                )})
                return
            self._reply(200, {"session": session_id,
                              "reset": engine.session_reset(session_id)})
            return

        # /session/embed: the trace starts BEFORE the body read, exactly
        # like the stateless handler — the parse span must hold the
        # socket read + json.loads (for big frames that IS the parse)
        rid_header = request_trace_id(self.headers.get("X-Request-Id"))
        remote = parse_traceparent(self.headers.get("traceparent"))
        root = tracer.start_trace(
            SPAN_REQUEST,
            trace_id=rid_header or (remote[0] if remote else None),
            parent_id=remote[1] if remote else None,
            attrs={"endpoint": "session"},
        )
        self._trace_root = root
        self._request_id = rid_header or root.trace_id
        tenant = self._tenant()
        if tenant == "":
            _t = tracer.clock()
            tracer.record(SPAN_PARSE, root, root.start, _t)
            tracer.end(root, attrs={"status": 400}, at=_t)
            return

        def _finish(status: int, latency_ms=None, at=None, version=None):
            tracer.end(root, attrs={"status": status}, at=at)
            engine.observe_outcome("session", latency_ms, status >= 500,
                                   trace_id=root.trace_id,
                                   tenant=tenant, version=version)

        payload = self._read_json()
        session_id = payload.get("session") if payload is not None else None
        if payload is not None:
            if not valid_session_id(session_id):
                self._reply(400, {"error": (
                    f"bad 'session' field {session_id!r}: want 1-128 chars "
                    f"of [A-Za-z0-9._:-]"
                )})
                payload = None
            else:
                root.attrs["session"] = session_id
        imgs = self._parse_images(payload) if payload is not None else None
        t_parsed = tracer.clock()
        tracer.record(SPAN_PARSE, root, root.start, t_parsed)
        if imgs is None:
            _finish(400)
            return
        import time as _time

        t0 = _time.monotonic()
        run = engine.session_parse if parse else engine.session_embed
        try:
            out, info = run(session_id, imgs, ctx=root, tenant=tenant)
        except TenantQuotaExceeded as e:
            self._reply(503, {"error": "tenant_overloaded",
                              "tenant": e.tenant,
                              "detail": "tenant admission quota exhausted; "
                                        "back off"})
            _finish(503)
            return
        except Closed:
            self._reply(503, {"error": "shutting_down",
                              "detail": "server is draining; retry elsewhere"})
            _finish(503)
            return
        except ValueError as e:  # oversize frame batch
            self._reply(400, {"error": str(e)})
            _finish(400)
            return
        except Exception as e:
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            _finish(500)
            return
        latency = _time.monotonic() - t0
        # tile the handler exactly like the stateless path: dispatch_wait
        # spans the whole inline execute window (the cache's execute span
        # overlaps inside it; union coverage dedupes) so a session trace
        # explains its wall time with no instrumentation gap
        t_done = tracer.clock()
        tracer.record(SPAN_DISPATCH_WAIT, root, t_parsed, t_done)
        engine.registry.histogram(
            "serving_latency_seconds_session"
            + ("_parse" if parse else ""),
            help=("session parse-frame latency, admission to response"
                  if parse else
                  "session frame latency, admission to response"),
            unit="seconds",
        ).observe(latency)
        resp = {
            "latency_ms": round(latency * 1e3, 3),
            "request_id": self._request_id,
            "session": session_id,
            **info,  # carries the honest "step" (the version that served)
        }
        if parse:
            from glom_tpu.hierarchy.parse import unpack_parse

            cfg = engine.config
            side = cfg.image_size // cfg.patch_size
            resp["islands"] = [
                unpack_parse(row, cfg.levels, side, cfg.dim) for row in out]
        else:
            level = payload.get("level")
            if level is not None:
                try:
                    out = out[:, int(level)]
                except (IndexError, TypeError, ValueError):
                    self._reply(400, {"error": (
                        f"level {level!r} outside this model's "
                        f"{engine.config.levels} levels"
                    )})
                    _finish(400)
                    return
            resp["embeddings"] = out.tolist()
        self._reply(200, resp)
        t_end = tracer.clock()
        tracer.record(SPAN_RESPOND, root, t_done, t_end)
        _finish(200, latency_ms=latency * 1e3, at=t_end,
                version=info.get("canary_step"))

    def do_POST(self):  # noqa: N802
        self._request_id = None  # reset before routing (keep-alive reuse)
        if self.path.startswith("/admin/reload/"):
            self._do_admin()
            return
        if self.path.startswith("/admin/deploy/"):
            self._do_deploy_admin()
            return
        if self.path.startswith("/admin/jobs/"):
            self._do_jobs_admin()
            return
        if self.path == "/admin/quality/ref":
            # freeze the CURRENT live quality distributions as the drift
            # reference profile (written next to the checkpoints, adopted
            # immediately — see glom_tpu.obs.quality)
            engine = self.server.engine
            try:
                path = engine.quality.save_reference(
                    engine.checkpoint_dir, step=int(engine.step))
            except OSError as e:
                self._reply(500, {"error": f"reference write failed: {e}"})
                return
            self._reply(200, {"written": path, "step": int(engine.step)})
            return
        if self.path in ("/session/embed", "/session/parse",
                         "/session/reset"):
            self._do_session()
            return
        if self.path == "/similar":
            self._do_similar()
            return
        if self.path not in ("/embed", "/reconstruct", "/parse"):
            self._reply(404, {"error": f"no route {self.path}"})
            return
        endpoint = self.path[1:]
        engine = self.server.engine
        tracer = engine.tracer

        # -- trace context: join the client's trace or mint a fresh one.
        # X-Request-Id wins (operators grep their own ids); a W3C
        # traceparent supplies trace + remote parent; either way the
        # identity is echoed back on EVERY reply (see _reply).
        rid_header = request_trace_id(self.headers.get("X-Request-Id"))
        remote = parse_traceparent(self.headers.get("traceparent"))
        root = tracer.start_trace(
            SPAN_REQUEST,
            trace_id=rid_header or (remote[0] if remote else None),
            parent_id=remote[1] if remote else None,
            attrs={"endpoint": endpoint},
        )
        self._trace_root = root
        self._request_id = rid_header or root.trace_id

        # multi-tenant + canary routing identities, resolved up front:
        # the tenant gates admission and labels the outcome; the canary
        # assignment is the DETERMINISTIC hash of the stickiest key the
        # request offers (affinity key, else its request id), so the
        # same caller lands on the same version for the whole deploy
        tenant = self._tenant()
        if tenant == "":
            _t = tracer.clock()
            tracer.record(SPAN_PARSE, root, root.start, _t)
            tracer.end(root, attrs={"status": 400}, at=_t)
            return
        deploy_key = self.headers.get("X-Affinity-Key") or self._request_id

        def _finish(status: int, latency_ms=None, at=None, version=None):
            tracer.end(root, attrs={"status": status}, at=at)
            engine.observe_outcome(endpoint, latency_ms, status >= 500,
                                   trace_id=root.trace_id,
                                   tenant=tenant, version=version)

        # The handler's own phases — parse / dispatch_wait / respond — are
        # recorded with SHARED edges (explicit timestamps) so they TILE
        # the request span: no instrumentation gap between them, and the
        # trace explains the whole handler wall time.  dispatch_wait
        # (parked on the result future) deliberately OVERLAPS the
        # pipeline's queue_wait/execute spans; union-based coverage
        # dedupes the overlap, and it holds the scheduling gaps (worker
        # wake, future wake) no pipeline stage can see.
        payload = self._read_json()
        model = payload.get("model") if payload is not None else None
        model_cfg = None
        if payload is not None and model is not None:
            record = engine.models.get(model)
            if record is None:
                self._reply(400, {"error": (
                    f"unknown model {model!r}; resident: "
                    f"{engine.models.models()}")})
                _finish(400)
                return
            model_cfg = record.config
            root.attrs["model"] = model
        imgs = (self._parse_images(payload, cfg=model_cfg)
                if payload is not None else None)
        t_parsed = tracer.clock()
        tracer.record(SPAN_PARSE, root, root.start, t_parsed)
        if imgs is None:
            _finish(400)
            return
        # extra models never canary (deploys guard the default model)
        version = engine.deploy.assign(deploy_key) if model is None else None
        import time as _time

        t0 = _time.monotonic()
        # outcome attribution: a request REJECTED before execution (quota
        # shed, queue shed, drain, validation) never touched the
        # candidate — charging it to the candidate's error budget would
        # let an overload unrelated to the deploy trigger a spurious
        # auto-rollback.  Only outcomes that (may have) executed on the
        # candidate keep the version tag.
        out_version = version
        try:
            future = engine.submit(endpoint, imgs, ctx=root, tenant=tenant,
                                   model=model, version=version)
            out = future.result(timeout=60.0)
        except TenantQuotaExceeded as e:
            error, code, body = e, 503, {
                "error": "tenant_overloaded", "tenant": e.tenant,
                "detail": "tenant admission quota exhausted; back off"}
            out_version = None
        except Overloaded as e:
            error, code, body = e, 503, {
                "error": "overloaded",
                "detail": "queue at capacity; retry with backoff"}
            out_version = None
        except Closed as e:
            error, code, body = e, 503, {
                "error": "shutting_down",
                "detail": "server is draining; retry elsewhere"}
            out_version = None
        except ValueError as e:  # e.g. request larger than max_batch
            error, code, body = e, 400, {"error": str(e)}
            out_version = None
        except Exception as e:
            error, code, body = e, 500, {"error": f"{type(e).__name__}: {e}"}
        else:
            error = None
        t_done = tracer.clock()
        tracer.record(SPAN_DISPATCH_WAIT, root, t_parsed, t_done)
        if error is not None:
            self._reply(code, body)
            _finish(code, version=out_version)
            return
        latency = _time.monotonic() - t0
        engine.registry.histogram(
            f"serving_latency_seconds_{endpoint}",
            help="request latency, admission to response", unit="seconds",
        ).observe(latency)

        # the step field is honest about WHICH version served: canary
        # responses carry the candidate step (chaos/loadgen count the
        # canary fraction from exactly this).  If the candidate was
        # retired while this request was in flight, the group fell back
        # to the primary — report the primary step, not the assignment
        # (the outcome still carries the version tag so the engine can
        # classify it as an orphan rather than primary-SLO evidence).
        served_version = version
        if (version is not None
                and engine.deploy.candidate_step != version):
            served_version = None
        resp = {"step": int(served_version) if served_version is not None
                else int(engine.step),
                "latency_ms": round(latency * 1e3, 3),
                "request_id": self._request_id}
        if model is not None:
            resp["model"] = model
        if endpoint == "embed":
            level = payload.get("level")
            if level is not None:
                try:
                    out = out[:, int(level)]
                except (IndexError, TypeError, ValueError):
                    self._reply(400, {"error": (
                        f"level {level!r} outside this model's "
                        f"{engine.config.levels} levels"
                    )})
                    t_end = tracer.clock()
                    tracer.record(SPAN_RESPOND, root, t_done, t_end)
                    _finish(400, at=t_end, version=version)
                    return
            resp["embeddings"] = out.tolist()
        elif endpoint == "parse":
            from glom_tpu.hierarchy.parse import unpack_parse

            cfg = model_cfg if model_cfg is not None else engine.config
            side = cfg.image_size // cfg.patch_size
            resp["islands"] = [
                unpack_parse(row, cfg.levels, side, cfg.dim) for row in out]
        else:
            resp["images"] = out.tolist()
        self._reply(200, resp)
        # root end SHARES the respond span's end edge: a preemption
        # between two separate clock reads would leak uncovered wall time
        t_end = tracer.clock()
        tracer.record(SPAN_RESPOND, root, t_done, t_end)
        _finish(200, latency_ms=latency * 1e3, at=t_end, version=version)

    # -- similarity queries (the /similar request path) --------------------
    # POST /similar: level-aware nearest-neighbor lookup against this
    # replica's index shards (docs/HIERARCHY.md).  Inline on the handler
    # thread like a session frame: the device half is one warmed AOT
    # executable, the scan is host-side mmap work.  Body: images plus
    # optional "level" (default: the top level) and "k" (default 5).
    def _do_similar(self):
        engine = self.server.engine
        tracer = engine.tracer
        if not engine.similar_enabled:
            self._reply(404, {"error": "similarity index disabled on this "
                                       "engine (start the server with "
                                       "--index-dir)"})
            return
        rid_header = request_trace_id(self.headers.get("X-Request-Id"))
        remote = parse_traceparent(self.headers.get("traceparent"))
        root = tracer.start_trace(
            SPAN_REQUEST,
            trace_id=rid_header or (remote[0] if remote else None),
            parent_id=remote[1] if remote else None,
            attrs={"endpoint": "similar"},
        )
        self._trace_root = root
        self._request_id = rid_header or root.trace_id
        tenant = self._tenant()
        if tenant == "":
            _t = tracer.clock()
            tracer.record(SPAN_PARSE, root, root.start, _t)
            tracer.end(root, attrs={"status": 400}, at=_t)
            return

        def _finish(status: int, latency_ms=None, at=None):
            tracer.end(root, attrs={"status": status}, at=at)
            engine.observe_outcome("similar", latency_ms, status >= 500,
                                   trace_id=root.trace_id, tenant=tenant)

        payload = self._read_json()
        imgs = self._parse_images(payload) if payload is not None else None
        t_parsed = tracer.clock()
        tracer.record(SPAN_PARSE, root, root.start, t_parsed)
        if imgs is None:
            _finish(400)
            return
        import time as _time

        t0 = _time.monotonic()
        try:
            level = payload.get("level")
            k = payload.get("k", 5)
            results, info = engine.similar(
                imgs, level=None if level is None else int(level),
                k=int(k), ctx=root, tenant=tenant)
        except TenantQuotaExceeded as e:
            self._reply(503, {"error": "tenant_overloaded",
                              "tenant": e.tenant,
                              "detail": "tenant admission quota exhausted; "
                                        "back off"})
            _finish(503)
            return
        except (TypeError, ValueError) as e:  # bad level/k, oversize batch
            self._reply(400, {"error": str(e)})
            _finish(400)
            return
        except Exception as e:
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            _finish(500)
            return
        latency = _time.monotonic() - t0
        t_done = tracer.clock()
        tracer.record(SPAN_DISPATCH_WAIT, root, t_parsed, t_done)
        engine.registry.histogram(
            "serving_latency_seconds_similar",
            help="similarity query latency, admission to response",
            unit="seconds",
        ).observe(latency)
        self._reply(200, {
            "step": int(engine.step),
            "latency_ms": round(latency * 1e3, 3),
            "request_id": self._request_id,
            "results": results,
            **info,
        })
        t_end = tracer.clock()
        tracer.record(SPAN_RESPOND, root, t_done, t_end)
        _finish(200, latency_ms=latency * 1e3, at=t_end)


def make_server(engine: ServingEngine, host: str = "127.0.0.1",
                port: int = 0, *, quiet: bool = True,
                metrics_timestamps: bool = False) -> ServingHTTPServer:
    """Bind (port 0 = ephemeral — tests read ``server.server_address``);
    the caller starts ``serve_forever`` on its own thread."""
    return ServingHTTPServer((host, port), _Handler, engine, quiet=quiet,
                             metrics_timestamps=metrics_timestamps)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="GLOM online serving: dynamic batching + bucketed AOT "
                    "compile cache + checkpoint hot-reload",
    )
    p.add_argument("--checkpoint-dir", required=True,
                   help="Trainer checkpoint dir (reads its config.json)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--buckets", default="1,2,4,8",
                   help="comma-separated batch buckets, padded up to")
    p.add_argument("--buckets-file", default=None,
                   help="JSON file holding the bucket ladder — either a "
                        "plain list or a tools/trace_report.py "
                        "--suggest-buckets payload (its 'suggested_buckets' "
                        "key); overrides --buckets.  The measured auto-tune "
                        "loop: serve with --trace-log, run trace_report "
                        "--suggest-buckets, restart with the emitted file")
    p.add_argument("--quant", default="f32", choices=["f32", "bf16", "int8"],
                   help="serving precision: bf16 = half-size weights + bf16 "
                        "compute; int8 = weight-only symmetric int8 "
                        "(dequantized in-graph, bf16 activations).  Gate a "
                        "non-f32 rollout on tools/quant_check.py first")
    p.add_argument("--ff-impl", default=None,
                   choices=["dense", "pallas", "fused"],
                   help="override the checkpoint config's kernel choice "
                        "(fused = single-launch level update)")
    p.add_argument("--mesh-shape", default=None,
                   help="serve mesh-sharded: comma '(data,model,seq)' device "
                        "counts, e.g. '1,4,1' = 4-way TP within this "
                        "replica.  Buckets must divide the data axis.  "
                        "Default: single-device replicated")
    p.add_argument("--param-sharding", default="replicated",
                   choices=["replicated", "tp", "ep"],
                   help="param placement on the mesh (parallel/sharding.py "
                        "rules): tp shards every level-MLP's hidden dim "
                        "over the model axis; ep shards whole level-nets")
    p.add_argument("--no-donate", action="store_true",
                   help="keep the executables' input image buffers "
                        "un-donated (debugging aid; donation is the default "
                        "off-CPU)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="micro-batch deadline: flush a partial batch after this")
    p.add_argument("--max-queue", type=int, default=64,
                   help="queued-image bound; beyond it requests shed (503)")
    p.add_argument("--iters", type=int, default=None,
                   help="GLOM iterations (default: the model's)")
    p.add_argument("--warm-iters", default=None, metavar="N|auto",
                   help="enable stateful sessions (/session/embed + "
                        "/session/reset): warm frames settle from the "
                        "previous frame's equilibrium in N iterations "
                        "('auto' = half the cold count).  Gate the value "
                        "with tools/session_check.py first")
    p.add_argument("--session-ttl-s", type=float, default=600.0,
                   help="idle sessions older than this are evicted")
    p.add_argument("--session-max-mb", type=float, default=256.0,
                   help="byte bound on resident session state; LRU "
                        "sessions evict beyond it")
    p.add_argument("--session-spill-dir", default=None,
                   help="spill session state here on drain and restore it "
                        "at startup (checkpoint npz format) — a rolling "
                        "restart keeps the fleet warm")
    p.add_argument("--reload-poll-s", type=float, default=2.0,
                   help="checkpoint hot-reload poll period; 0 disables")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the startup AOT compile pass (first requests "
                        "per bucket then pay the compile)")
    p.add_argument("--warmup-dir", default=None,
                   help="write per-bucket HLO/cost snapshots here at warmup")
    p.add_argument("--forensics-dir", default=None,
                   help="bundle root for queue_saturation/slo_burn captures")
    p.add_argument("--trace-log", default=None,
                   help="JSONL file receiving one record per completed "
                        "request trace (tools/trace_report.py reads it)")
    p.add_argument("--slo", action="append", default=None, metavar="SPEC",
                   help="declarative SLO target, repeatable: 'embed:p95<250ms' "
                        "(latency) or 'errors<1%%' (error rate); burn fires "
                        "the slo_burn forensics trigger")
    p.add_argument("--tenant-quota", action="append", default=None,
                   metavar="NAME=RATE[:BURST]",
                   help="repeatable: per-tenant admission quota in "
                        "images/s (token bucket; burst defaults to the "
                        "rate).  Requests carry X-Tenant; a tenant past "
                        "its bucket sheds 503 WITHOUT touching other "
                        "tenants' admission or latency")
    p.add_argument("--model", action="append", default=None,
                   metavar="NAME=DIR", dest="models",
                   help="repeatable: load an extra named model from its "
                        "own checkpoint dir, resident alongside the "
                        "default (request it with a \"model\" field)")
    p.add_argument("--deploy-pin-url", default=None, metavar="URL",
                   help="fleet router base URL: deploy promote/rollback "
                        "converge every replica through its two-phase "
                        "POST /rollout instead of a local-only swap")
    p.add_argument("--deploy-promote-after", type=int, default=3,
                   help="clean candidate burn windows before auto-promote")
    p.add_argument("--deploy-window-s", type=float, default=None,
                   help="candidate burn-window length (default: the "
                        "longest SLO short window)")
    p.add_argument("--deploy-min-events", type=int, default=None,
                   help="candidate outcomes a window needs to count as "
                        "evidence (default: the smallest SLO min_events)")
    p.add_argument("--deploy-canary-fraction", type=float, default=0.1,
                   help="default live-traffic fraction for begin_canary")
    p.add_argument("--capacity-policy", default=None, metavar="SPEC",
                   help="dry-run autoscale advisor policy, e.g. "
                        "'p95_ms<250,duty<0.8,shed<0.01' — evaluated over "
                        "the capacity series every window; violations emit "
                        "RECOMMENDATIONS only (GET /capacity), never act")
    p.add_argument("--capacity-ceiling", type=float, default=None,
                   help="measured imgs/s/chip ceiling for utilization "
                        "accounting (default: newest BENCH_*.json "
                        "last_measured in the repo root)")
    p.add_argument("--capacity-window-s", type=float, default=30.0,
                   help="capacity signal window (duty/shed/rate deltas "
                        "are computed over this span)")
    p.add_argument("--capacity-persist-windows", type=int, default=5,
                   help="consecutive scale-up windows before the advisor "
                        "fires the debounced capacity_pressure forensics "
                        "incident")
    p.add_argument("--bulk-dir", default=None, metavar="DIR",
                   help="enable the bulk inference tier: job-store "
                        "directory for scavenger-class offline jobs "
                        "(docs/BULK.md); unfinished jobs in the store "
                        "resume automatically on start")
    p.add_argument("--index-dir", default=None, metavar="DIR",
                   help="enable POST /similar: root of a level-aware "
                        "similarity index built by a bulk 'index' job "
                        "(docs/HIERARCHY.md).  The directory may fill in "
                        "later; queries see whatever parts exist")
    p.add_argument("--parse-thresholds", default=None, metavar="T|T0,T1,..",
                   help="agreement threshold(s) for POST /parse islanding: "
                        "one float broadcast to every level, or one per "
                        "level, comma-separated, each in [-1, 1] "
                        "(default 0.9)")
    p.add_argument("--quality-sample", type=float, default=1.0,
                   help="fraction of served batches fed through the "
                        "model-quality post-pass (island agreement, "
                        "residual, drift sketches — GET /quality).  "
                        "Deterministic credit sampling; 0 disables the "
                        "plane entirely")
    p.add_argument("--metrics-timestamps", action="store_true",
                   help="stamp /metrics samples with unix seconds on "
                        "OpenMetrics-negotiated scrapes (aligns scraped "
                        "series with the internal /debug/series windows)")
    p.add_argument("--demo", action="store_true",
                   help="write a tiny demo checkpoint into --checkpoint-dir "
                        "if it has none (smoke runs)")
    p.add_argument("--platform", default="auto",
                   help="force a JAX platform (e.g. 'cpu')")
    p.add_argument("--verbose", action="store_true", help="per-request access log")
    args = p.parse_args(argv)

    if args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)

    from glom_tpu import checkpoint as ckpt_lib

    if args.demo and ckpt_lib.latest_step(args.checkpoint_dir) is None:
        from glom_tpu.serving.engine import make_demo_checkpoint

        make_demo_checkpoint(args.checkpoint_dir)
        print(json.dumps({"event": "demo_checkpoint", "dir": args.checkpoint_dir}))

    buckets = tuple(int(b) for b in args.buckets.split(","))
    if args.buckets_file:
        with open(args.buckets_file) as f:
            ladder = json.load(f)
        if isinstance(ladder, dict):
            ladder = ladder.get("suggested_buckets")
        if not (isinstance(ladder, list) and ladder
                and all(isinstance(b, int) and b >= 1 for b in ladder)):
            raise SystemExit(
                f"--buckets-file {args.buckets_file!r} holds no usable "
                f"ladder (want a list of ints or a --suggest-buckets payload)"
            )
        buckets = tuple(ladder)

    engine = ServingEngine(
        args.checkpoint_dir,
        buckets=buckets,
        iters=args.iters,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        reload_poll_s=args.reload_poll_s,
        warmup=not args.no_warmup,
        warmup_dir=args.warmup_dir,
        forensics_dir=args.forensics_dir,
        trace_log=args.trace_log,
        slos=args.slo,
        quant=args.quant,
        ff_impl=args.ff_impl,
        donate_inputs=False if args.no_donate else None,
        mesh_shape=(tuple(int(s) for s in args.mesh_shape.split(","))
                    if args.mesh_shape else None),
        param_sharding=args.param_sharding,
        # passed through raw: the engine normalizes None/'auto'/int
        warm_iters=args.warm_iters,
        session_ttl_s=args.session_ttl_s,
        session_max_bytes=int(args.session_max_mb * 2 ** 20),
        session_spill_dir=args.session_spill_dir,
        tenant_quotas=(
            {name: spec for name, spec in
             (entry.split("=", 1) for entry in args.tenant_quota)}
            if args.tenant_quota else None),
        extra_models=(
            {name: path for name, path in
             (entry.split("=", 1) for entry in args.models)}
            if args.models else None),
        deploy_promote_after=args.deploy_promote_after,
        deploy_window_s=args.deploy_window_s,
        deploy_min_events=args.deploy_min_events,
        deploy_canary_fraction=args.deploy_canary_fraction,
        deploy_pin_url=args.deploy_pin_url,
        capacity_policy=args.capacity_policy,
        capacity_window_s=args.capacity_window_s,
        capacity_persist_windows=args.capacity_persist_windows,
        capacity_ceiling=(args.capacity_ceiling
                          if args.capacity_ceiling is not None
                          else read_bench_ceiling()),
        quality_sample=args.quality_sample,
        bulk_dir=args.bulk_dir,
        parse_thresholds=args.parse_thresholds,
        index_dir=args.index_dir,
    )
    engine.start()
    engine.capacity.start()  # sampler thread: tests tick() with a fake clock
    server = make_server(engine, args.host, args.port, quiet=not args.verbose,
                         metrics_timestamps=args.metrics_timestamps)

    # SIGTERM/SIGINT -> graceful drain, mirroring the trainer's preemption
    # path: stop admission, flush queued batches, then stop accepting
    stop_once = threading.Event()

    def _graceful(signum, frame):
        if stop_once.is_set():
            return
        stop_once.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    host, port = server.server_address[:2]
    print(json.dumps({
        "event": "serving", "host": host, "port": port,
        "step": int(engine.step), "buckets": engine.health()["buckets"],
        "warm": engine.health()["warm"], "quant": engine.quant,
        "ff_impl": engine.config.ff_impl,
        "mesh": engine.health()["mesh"],
        "param_sharding": engine.param_sharding,
    }), flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        engine.shutdown(drain=True)
        server.server_close()
        print(json.dumps({"event": "drained", "step": int(engine.step)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
