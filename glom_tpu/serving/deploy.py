"""Safe-deploy state machine: shadow -> canary -> promote | rollback.

Turns the engine's hot-reload primitive into a *guarded* rollout
(ROADMAP item 4; the ops sections of the Gemma serving comparison,
arXiv:2605.25645).  A new checkpoint never takes 100% of traffic in one
step:

  * **shadow** (:meth:`DeployController.begin_shadow`) — the candidate
    step is loaded resident (a second
    :class:`~glom_tpu.serving.registry.ModelVersion` of the default
    model, serving through the ALIASED AOT compile caches: zero new
    compiles).  Live batches are mirrored onto a bounded queue and
    re-executed against the candidate params on a dedicated shadow
    thread; responses are discarded, and the latency/error outcomes are
    recorded under the CANDIDATE's burn-rate evaluators only — never the
    primary's SLO accounting, and never the primary's request path (a
    full shadow queue drops the mirror, counted, rather than backing up
    the worker);

  * **canary** (:meth:`begin_canary`) — a deterministic weighted
    fraction of live traffic executes against the candidate:
    :meth:`assign` hashes the request's affinity key with the candidate
    step as salt, so the same key always lands on the same side and a
    session never straddles versions mid-stream (the engine additionally
    pins a session with resident state to the version that computed it);

  * **auto-promote** — after ``promote_after`` consecutive CLEAN
    burn-rate windows (each ``window_s`` long, holding at least
    ``min_events`` candidate outcomes, with no evaluator breaching), the
    candidate becomes primary: through the router's two-phase coordinated
    rollout when ``pin_url`` is set (the whole fleet flips atomically —
    never half-old/half-new), by a local atomic swap otherwise;

  * **auto-rollback** — the moment any candidate evaluator's
    SHORT-window burn rate crosses its threshold (latency burn or
    error-rate breach; the long window is deliberately not required —
    retreat is cheap, a slow page is not), the candidate is retired, a
    ``deploy_rollback`` forensics bundle is captured naming the
    offending trace IDs (spans attached while the tracer retains them)
    and the before/after version pins, and ``pin_url`` (when set) is
    re-pinned to the old step through the same two-phase rollout so
    every replica converges back.

The controller is transport-agnostic (``http`` injectable) and runs on
the engine's injectable clock; all state transitions are serialized
under one lock, with the expensive tails (bundle write, fleet pin HTTP)
executed after the state flip so a rollback can never be raced into
firing twice.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

from glom_tpu.obs.slo import SLO, BurnRateEvaluator, parse_slo
from glom_tpu.obs.triggers import TRIGGER_DEPLOY_ROLLBACK
from glom_tpu.resilience import faultinject

PHASES = ("idle", "shadow", "canary")


def _cosine_divergence(a, b, eps: float = 1e-8):
    """``(1 - mean cosine, per-level list)`` between two output arrays
    of identical shape.  Embedding outputs ``(b, L, d)`` compare per
    (image, level) vector — the per-level view shows WHICH level of the
    part-whole hierarchy a candidate disagrees at; any other shape
    (reconstructions ``(b, c, H, W)``) flattens per image.  Host-side
    NumPy on already-fetched outputs: no device work, no compiles."""
    import numpy as np

    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    if a.ndim == 3:                       # (b, L, d): per-level vectors
        a2, b2 = a, b
    else:                                 # flatten per image
        a2 = a.reshape(a.shape[0], 1, -1)
        b2 = b.reshape(b.shape[0], 1, -1)
    dot = (a2 * b2).sum(axis=-1)
    denom = (np.linalg.norm(a2, axis=-1) * np.linalg.norm(b2, axis=-1))
    cos = dot / np.maximum(denom, eps)    # (b, L)
    per_level = [float(1.0 - c) for c in cos.mean(axis=0)]
    return float(1.0 - cos.mean()), per_level

#: candidate guardrails when the engine has no SLOs configured: a deploy
#: with no declared objectives still rolls back on a plainly-broken
#: candidate (error storm) — guarded exposure must not be opt-in
DEFAULT_CANDIDATE_SLOS = ("errors<2%",)

#: the quality guardrail every deploy gets (unless the operator declared
#: their own ``divergence`` objective): shadow-mirrored batches run on
#: BOTH versions, and a candidate whose outputs diverge from the
#: primary's on the same inputs burns this budget and rolls back —
#: a fast-but-wrong candidate is a regression even with perfect latency
DEFAULT_QUALITY_SLOS = ("divergence<0.2",)


class _Candidate:
    """One immutable-ish active-deploy record: readers (assign, the
    request path) take ONE reference read; all mutation replaces the
    reference under the controller lock."""

    def __init__(self, step: int, version, phase: str, fraction: float):
        self.step = int(step)
        self.version = version            # registry.ModelVersion
        self.phase = phase                # "shadow" | "canary"
        self.fraction = float(fraction)


class DeployController:
    """Shadow/canary lifecycle for the engine's ``default`` model."""

    def __init__(self, engine, *, promote_after: int = 3,
                 window_s: Optional[float] = None,
                 min_events: Optional[int] = None,
                 canary_fraction: float = 0.1,
                 shadow_queue: int = 8,
                 pin_url: Optional[str] = None,
                 pin_timeout_s: float = 120.0,
                 http=None):
        self.engine = engine
        self.metrics = engine.registry
        self._clock = engine.tracer.clock
        if promote_after < 1:
            raise ValueError(f"promote_after must be >= 1, got "
                             f"{promote_after}")
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(f"canary_fraction must be in (0, 1], got "
                             f"{canary_fraction}")
        self.promote_after = promote_after
        self.default_fraction = canary_fraction
        self.pin_url = pin_url.rstrip("/") if pin_url else None
        self.pin_timeout_s = pin_timeout_s
        self._http = http
        # candidate objectives: the engine's declared SLOs when present
        # (same promises, applied to the candidate's outcomes), the
        # error-storm guardrail otherwise
        base = [ev.slo for ev in engine._slo.evaluators] if (
            engine._slo is not None) else [
            parse_slo(s) for s in DEFAULT_CANDIDATE_SLOS]
        self._slos: List[SLO] = list(base)
        self.window_s = float(window_s) if window_s is not None else max(
            s.short_window_s for s in self._slos)
        self.min_events = int(min_events) if min_events is not None else min(
            s.min_events for s in self._slos)
        # the quality guardrail rides the candidate's own cadence
        # (windows/min_events resolved above), so shadow traffic can
        # burn it as fast as it can burn a latency objective
        if not any(s.kind == "quality" and s.metric == "divergence"
                   for s in self._slos):
            self._slos.extend(
                parse_slo(spec, short_window_s=self.window_s,
                          long_window_s=max(
                              [self.window_s]
                              + [s.long_window_s for s in self._slos]),
                          min_events=self.min_events,
                          burn_threshold=min(
                              s.burn_threshold for s in self._slos))
                for spec in DEFAULT_QUALITY_SLOS)

        self._lock = threading.Lock()
        # serializes whole begin_* calls INCLUDING the candidate load (a
        # slow restore): two concurrent begins must not both load — the
        # loser's param tree would stay resident with nothing to retire
        # it.  Ordered strictly before _lock; never taken by the hot
        # paths (assign/mirror/observe) or the settle verbs.
        self._begin_lock = threading.Lock()
        self._cand: Optional[_Candidate] = None
        # candidate steps retired by rollback/abort: a session whose
        # resident state one of them computed must cold-restart rather
        # than warm-iterate a retired version's equilibrium on primary
        # params (bounded; a re-deploy of the step clears it)
        self._retired_steps: "deque" = deque(maxlen=8)
        self._evaluators: List[BurnRateEvaluator] = []
        # clean-window accounting (guarded by _lock)
        self._window_start = 0.0
        self._window_events = 0
        self._window_breached = False
        self._clean_windows = 0
        # offender ring: trace ids of recent BAD candidate outcomes (the
        # rollback bundle's evidence, kept even when an SLO's own short
        # window has rotated them out)
        self._offenders: "deque" = deque(maxlen=20)
        self.last_report: Optional[dict] = None
        # -- shadow executor ------------------------------------------------
        self._shadow_q: "deque" = deque(maxlen=shadow_queue)
        self._shadow_cv = threading.Condition()
        self._shadow_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- introspection -----------------------------------------------------
    @property
    def phase(self) -> str:
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        return cand.phase if cand is not None else "idle"

    @property
    def active(self) -> bool:
        return self._cand is not None  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params

    @property
    def candidate_step(self) -> Optional[int]:
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        return cand.step if cand is not None else None

    def candidate(self, step: Optional[int] = None):
        """The candidate's (params, caches) for the engine's partitioned
        execute — or None when retired (in-flight canary items then
        finish on the primary: safe, and exactly the post-rollback
        contract).  A ``step`` pins the lookup (an item tagged for a
        candidate that was since replaced must not run on the new one)."""
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        if cand is None or (step is not None and cand.step != step):
            return None
        return cand.version

    def status(self) -> dict:
        """The ``/healthz`` ``deploy`` block + ``/admin/deploy/status``."""
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        with self._lock:
            clean = self._clean_windows
        return {
            "phase": "idle" if cand is None else cand.phase,
            "candidate_step": None if cand is None else cand.step,
            "canary_fraction": None if cand is None else cand.fraction,
            "clean_windows": clean,
            "promote_after": self.promote_after,
            "window_s": self.window_s,
            "min_events": self.min_events,
            "pin_url": self.pin_url,
            "last": self.last_report,
        }

    # -- lifecycle ---------------------------------------------------------
    def begin_shadow(self, step: Optional[int] = None) -> Optional[int]:
        """Load the candidate resident and start mirroring.  ``step=None``
        targets the newest checkpoint that verifies and is newer than the
        serving step.  Returns the candidate step, or None when there is
        nothing (or nothing loadable) to deploy — a corrupt candidate is
        quarantined by the load path and never becomes resident, so a bad
        artifact aborts the deploy before any traffic touches it."""
        return self._begin("shadow", step, self.default_fraction)

    def begin_canary(self, fraction: Optional[float] = None,
                     step: Optional[int] = None) -> Optional[int]:
        """Route ``fraction`` of live traffic to the candidate.  Usable
        straight from idle (shadow is the recommended first phase, not a
        hard precondition) or to advance an active shadow; window
        accounting restarts either way — promotion needs ``promote_after``
        clean windows of CANARY exposure."""
        if fraction is not None and not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        return self._begin("canary", step,
                           fraction if fraction is not None
                           else self.default_fraction)

    def _begin(self, phase: str, step: Optional[int],
               fraction: float) -> Optional[int]:
        # _begin_lock spans check + load + install: without it, two
        # concurrent begins both pass the no-active-candidate check and
        # both load — the overwritten loser's device tree would stay
        # registered with no settle verb ever able to retire it
        with self._begin_lock:
            with self._lock:
                cand = self._cand
                if cand is not None and (step is None or step == cand.step):
                    # phase advance of the existing candidate
                    self._cand = _Candidate(cand.step, cand.version, phase,
                                            fraction)
                    self._reset_windows()
                    self._note_phase(phase, cand.step)
                    return cand.step
                if cand is not None:
                    # a DIFFERENT step while one is active: explicit abort
                    # first — two candidates at once is two extra param
                    # trees and an ambiguous assign()
                    raise RuntimeError(
                        f"deploy of step {cand.step} is active "
                        f"({cand.phase}); promote/rollback/abort it before "
                        f"deploying step {step}")
            version = self._load_candidate(step)
            if version is None:
                return None
            with self._lock:
                self._cand = _Candidate(version.step, version, phase,
                                        fraction)
                # a re-deployed step is no longer "retired": sessions it
                # serves from here on are current, not stale
                if version.step in self._retired_steps:
                    self._retired_steps.remove(version.step)
                self._reset_windows()
                self._evaluators = [
                    BurnRateEvaluator(s, clock=self._clock)
                    for s in self._slos]
                self._note_phase(phase, version.step)
        self._ensure_shadow_thread()
        return version.step

    def _load_candidate(self, step: Optional[int]):
        """Resident-load the candidate through the engine's restore path
        (quantize-like-startup + place + CRC verification with quarantine
        on corruption) and register it in the model registry with the
        ALIASED cache namespace — same config/quant/buckets by
        construction, so the shadow/canary path reuses the primary's AOT
        executables and the zero-request-path-compile invariant holds."""
        from glom_tpu import checkpoint as ckpt_lib
        from glom_tpu.resilience import integrity
        from glom_tpu.serving.registry import DEFAULT_MODEL

        engine = self.engine
        if step is None:
            step = integrity.latest_valid_step(
                engine.checkpoint_dir, observer=engine._integrity_obs,
                newer_than=engine.step)
            if step is None or step <= engine.step:
                return None
        step = int(step)
        existing = engine.models.get(DEFAULT_MODEL, step)
        if existing is not None and existing.role == "candidate":
            return existing
        if existing is not None:
            # pinned to what already serves (or a still-resident record):
            # nothing to deploy — mirror stage_reload's trivially-current
            # contract rather than erroring
            return None
        try:
            params = engine._restore_placed(step)
        except ckpt_lib.CorruptCheckpointError as e:
            integrity.quarantine(engine.checkpoint_dir, step,
                                 observer=engine._integrity_obs,
                                 reason=str(e))
            self._load_failure(step, e)
            return None
        except Exception as e:
            self._load_failure(step, e)
            return None
        # chaos seam: a candidate whose weights are corrupted AFTER the
        # integrity check — it loads clean, serves fast, and is WRONG.
        # Only the shadow lane's quality comparison can catch this class
        # of regression (CRC passed, latency/error SLOs stay green).
        if faultinject.fire("candidate_load") == "bitflip":
            import jax

            params = jax.tree_util.tree_map(lambda leaf: -leaf, params)
        primary = engine.models.get(DEFAULT_MODEL)
        return engine.models.register(
            DEFAULT_MODEL, step, params=params,
            caches=primary.caches, config=primary.config,
            train_cfg=primary.train_cfg, signature=primary.signature,
            source_dir=engine.checkpoint_dir, quant=engine.quant,
            role="candidate", aliased=True,
        )

    def _load_failure(self, step: int, e: Exception) -> None:
        self.metrics.counter(
            "deploy_candidate_load_failures",
            help="deploys aborted because the candidate checkpoint "
                 "would not load/verify",
        ).inc()
        warnings.warn(
            f"deploy candidate step {step} failed to load "
            f"({type(e).__name__}: {e}); deploy aborted, primary "
            f"untouched", stacklevel=3)

    def promote(self) -> Optional[dict]:
        """Candidate -> primary.  With ``pin_url``, the flip runs through
        the router's two-phase rollout (`POST /rollout {"step": N}`):
        every replica stages then commits the same step behind the
        dispatch gate, so the fleet is never half-old/half-new.  Without
        one, the local engine swaps atomically (keeping the displaced
        tree as its staged-API rollback point)."""
        with self._lock:
            cand = self._cand
            if cand is None:
                return None
            self._cand = None
            self._stop_evaluating()
        self._note_idle()
        old_step = int(self.engine.step)
        pin = self._pin_fleet(cand.step)
        if int(self.engine.step) != cand.step:
            # no router, a pin that could not commit, or a router whose
            # fleet does not include this engine: the local atomic swap
            # is the fallback so a promote never half-applies.  (A
            # successful pin already flipped this engine through its own
            # /admin/reload staged commit, which re-anchored the
            # registry's primary record.)
            self.engine.promote_candidate(cand.step)
        report = {
            "action": "promoted", "step": cand.step,
            "from_step": old_step, "fleet_pin": pin,
            "t": round(self._clock(), 3),
        }
        self.metrics.counter(
            "deploy_promotes_total",
            help="candidates promoted to primary after clean burn windows",
        ).inc()
        self._note_event("deploy_promote", step=int(cand.step),
                         from_step=old_step)
        self.last_report = report
        return report

    def rollback(self, reason: str = "operator",
                 detail: Optional[dict] = None) -> Optional[dict]:
        """Retire the candidate and converge the fleet back onto the old
        pin.  Fires the ``deploy_rollback`` forensics bundle: the reason,
        the before/after version pins, the offending trace IDs (with
        spans attached while the tracer retains them), and the candidate
        burn rates at the moment of retreat."""
        with self._lock:
            cand = self._cand
            if cand is None:
                return None
            self._cand = None
            if cand.step not in self._retired_steps:
                self._retired_steps.append(cand.step)
            offenders = list(self._offenders)
            rates = {ev.slo.name: ev.burn_rates()
                     for ev in self._evaluators}
            self._stop_evaluating()
        self._note_idle()
        old_step = int(self.engine.step)
        self.engine.models.remove("default", cand.step)
        pin = self._pin_fleet(old_step)
        report = {
            "action": "rolled_back", "reason": reason,
            "step": old_step, "candidate_step": cand.step,
            "pins": {"before": cand.step, "after": old_step},
            "phase_at_rollback": cand.phase,
            "fleet_pin": pin, "t": round(self._clock(), 3),
        }
        if detail:
            report.update(detail)
        self.metrics.counter(
            "deploy_rollbacks_total",
            help="candidate deploys auto/operator-rolled-back",
        ).inc()
        self._note_event("deploy_rollback", step=old_step,
                         candidate_step=int(cand.step), reason=reason)
        self._capture_rollback(report, offenders, rates)
        self.last_report = report
        return report

    def abort(self) -> bool:
        """Drop the candidate with no forensics (operator changed their
        mind / a failed begin elsewhere) — nothing burned, nothing to
        document.  Returns True when a candidate was resident."""
        with self._lock:
            cand = self._cand
            if cand is None:
                return False
            self._cand = None
            if cand.step not in self._retired_steps:
                self._retired_steps.append(cand.step)
            self._stop_evaluating()
        self._note_idle()
        self.engine.models.remove("default", cand.step)
        self._note_event("deploy_abort", candidate_step=int(cand.step))
        self.last_report = {"action": "aborted",
                            "candidate_step": cand.step,
                            "t": round(self._clock(), 3)}
        return True

    def close(self) -> None:
        """Engine shutdown: stop the shadow thread."""
        self._stop.set()
        with self._shadow_cv:
            self._shadow_cv.notify_all()
        t = self._shadow_thread
        if t is not None:
            t.join(timeout=5.0)
        self._shadow_thread = None

    def _stop_evaluating(self) -> None:
        # caller holds _lock
        self._evaluators = []
        self._reset_windows()
        with self._shadow_cv:
            self._shadow_q.clear()

    def _reset_windows(self) -> None:
        self._window_start = self._clock()
        self._window_events = 0
        self._window_breached = False
        self._clean_windows = 0
        self._offenders.clear()

    def retired(self, step: int) -> bool:
        """Whether ``step`` is a candidate a rollback/abort retired — a
        session whose resident state it computed must cold-restart
        instead of warm-iterating a retired version's equilibrium."""
        with self._lock:
            return step in self._retired_steps

    def _note_idle(self) -> None:
        """Terminal transition: the gauges must not report a phantom
        deploy forever (phase/candidate stick at their begin-time values
        otherwise — exactly what a dashboard alert would page on)."""
        self.metrics.gauge(
            "deploy_phase",
            help="deploy state machine: 0 idle, 1 shadow, 2 canary",
        ).set(0)
        self.metrics.gauge(
            "deploy_candidate_step",
            help="checkpoint step of the active deploy candidate",
        ).set(-1)
        self.metrics.gauge(
            "deploy_clean_windows",
            help="consecutive clean candidate burn windows",
        ).set(0)

    def _note_phase(self, phase: str, step: int) -> None:
        self.metrics.gauge(
            "deploy_phase",
            help="deploy state machine: 0 idle, 1 shadow, 2 canary",
        ).set(PHASES.index(phase))
        self.metrics.counter(
            self.metrics.labeled("deploy_phase_enters_", phase),
            help="deploy phase transitions",
        ).inc()
        self.metrics.gauge(
            "deploy_candidate_step",
            help="checkpoint step of the active deploy candidate",
        ).set(step)
        # unified timeline record (obs.events): the attribution plane
        # correlates these transitions with the regression knee.  Leaf
        # lock only, so safe under _lock.
        self._note_event(f"deploy_{phase}", step=int(step))

    def _note_event(self, event: str, **fields) -> None:
        timeline = getattr(self.engine, "timeline", None)
        if timeline is not None:
            timeline.note(event, **fields)

    # -- canary assignment -------------------------------------------------
    def assign(self, key: Optional[str]) -> Optional[int]:
        """The canary routing decision for one request: the candidate
        step when ``key`` hashes into the canary fraction, else None
        (primary).  Deterministic in (candidate step, key): the same
        affinity key always lands on the same side for the whole deploy,
        on every replica running the same controller — a session or a
        sticky client never flaps between versions."""
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        if cand is None or cand.phase != "canary" or not key:
            return None
        h = int(hashlib.sha1(
            f"{cand.step}:{key}".encode()).hexdigest()[:8], 16)
        return cand.step if (h / 0xFFFFFFFF) < cand.fraction else None

    # -- shadow mirroring --------------------------------------------------
    def mirror(self, endpoint: str, imgs, primary_out=None) -> None:
        """Offer one primary batch to the shadow executor, together with
        the PRIMARY's outputs for the same batch (the quality-comparison
        baseline — both sides then ran identical inputs).  Non-blocking
        and lossy by design: the mirror must never add latency to the
        primary path, so a backed-up shadow queue DROPS (counted) — the
        shadow is a measurement sample, not a delivery guarantee."""
        cand = self._cand  # glomlint: disable=conc-unguarded-attr -- atomic reference snapshot: _cand is only ever REPLACED under _lock (never mutated in place); a one-reference read is the documented lock-free fast path, like engine._params
        if cand is None or cand.phase != "shadow":
            return
        with self._shadow_cv:
            if len(self._shadow_q) == self._shadow_q.maxlen:
                self.metrics.counter(
                    "deploy_shadow_dropped",
                    help="mirrored batches dropped at the shadow queue "
                         "bound (primary path stays unblocked)",
                ).inc()
                return
            self._shadow_q.append((endpoint, imgs, cand.step, primary_out))
            self._shadow_cv.notify()

    def _ensure_shadow_thread(self) -> None:
        if self._shadow_thread is not None and self._shadow_thread.is_alive():
            return
        t = threading.Thread(target=self._shadow_loop,
                             name="glom-deploy-shadow", daemon=True)
        t.start()
        self._shadow_thread = t

    def _shadow_loop(self) -> None:
        while not self._stop.is_set():
            with self._shadow_cv:
                while not self._shadow_q and not self._stop.is_set():
                    self._shadow_cv.wait(timeout=0.25)
                if self._stop.is_set():
                    return
                endpoint, imgs, step, primary_out = self._shadow_q.popleft()
            self.process_shadow(endpoint, imgs, step, primary_out)

    def process_shadow(self, endpoint: str, imgs, step: int,
                       primary_out=None) -> bool:
        """Execute one mirrored batch against the candidate and JUDGE
        the result against the primary's outputs for the same inputs:
        per-level cosine divergence plus the candidate's island-parse
        agreement (through the engine's AOT-warmed quality post-pass —
        zero compiles).  The outcome (latency incl. any injected
        candidate fault, error, quality signals) feeds ONLY the
        candidate evaluators — shadow responses never reach a client.
        Public so tests can pump the shadow path deterministically
        without the thread."""
        version = self.candidate(step)
        if version is None:
            return False
        tracer = self.engine.tracer
        span = tracer.start_trace("shadow_execute", attrs={
            "endpoint": endpoint, "candidate_step": int(step)})
        t0 = self._clock()
        error = False
        quality = None
        try:
            kind = faultinject.fire("candidate")
            if kind == "error":
                raise faultinject.FaultError("injected candidate error")
            out = version.caches[endpoint](version.params, imgs)
            quality = self._shadow_quality(endpoint, version, imgs, out,
                                           primary_out)
            del out  # compared, never delivered: shadow stays invisible
            if kind == "delay":
                time.sleep(self.fault_delay_s)  # glomlint: disable=conc-raw-clock -- deliberate injected wall-clock stall: the fault simulates a genuinely slow candidate kernel
        except Exception as e:
            error = True
            span.attrs["error"] = repr(e)
        latency_ms = (self._clock() - t0) * 1e3
        tracer.end(span)
        self.metrics.counter(
            "deploy_shadow_requests",
            help="mirrored batches executed against the candidate",
        ).inc()
        self.observe_candidate(endpoint, None if error else latency_ms,
                               error, trace_id=span.trace_id,
                               quality=quality)
        return True

    def _shadow_quality(self, endpoint: str, version, imgs, out,
                        primary_out) -> Optional[Dict[str, float]]:
        """Quality signals for one shadow comparison: ``divergence`` =
        1 - mean per-level cosine between primary and candidate outputs
        on the SAME batch (the direct is-it-the-same-model measure), and
        the candidate's own ``agreement``/``residual`` from the quality
        post-pass (does the candidate still PARSE — a candidate can
        diverge because it is better, but a collapsed parse is not).
        Best-effort: a missing primary baseline or quality cache just
        omits those keys."""
        import numpy as np

        signals: Dict[str, float] = {}
        if primary_out is not None:
            div, per_level = _cosine_divergence(
                np.asarray(primary_out), np.asarray(out))
            signals["divergence"] = div
            self.metrics.gauge(
                "deploy_shadow_divergence",
                help="1 - mean cosine(primary, candidate) on mirrored "
                     "batches",
            ).set(round(div, 6))
            for i, d in enumerate(per_level):
                self.metrics.gauge(
                    f"deploy_shadow_divergence_l{i}",
                    help="per-level primary-vs-candidate cosine "
                         "divergence",
                ).set(round(d, 6))
            self.metrics.counter(
                "deploy_shadow_compared",
                help="mirrored batches judged primary-vs-candidate",
            ).inc()
        engine = self.engine
        qc = getattr(engine, "quality_cache", None)
        if qc is not None and getattr(imgs, "ndim", 0) == 4:
            try:
                mat = np.asarray(qc(version.params, imgs))
                levels = engine.config.levels
                signals["agreement"] = float(mat[:, :levels].mean())
                signals["residual"] = float(mat[:, 3 * levels].mean())
                engine.poll_quality_compiles()
                self.metrics.gauge(
                    "deploy_shadow_agreement",
                    help="candidate island agreement on mirrored batches",
                ).set(round(signals["agreement"], 6))
            except Exception:  # glomlint: disable=conc-broad-except -- the comparison is evidence, not a dependency: a failed post-pass must not fail the mirror
                pass
        return signals or None

    #: wall-seconds one injected ``candidate:delay`` fault adds (the
    #: chaos scenario's "latency-injected checkpoint")
    fault_delay_s = 0.25

    def injected_fault(self) -> Optional[str]:
        """The canary-path injection point (the engine calls this around
        a candidate group's execute): returns the armed fault kind —
        ``delay`` stalls the candidate batch (client-visible latency,
        never an error), ``error`` fails it (deliberately client-visible;
        the chaos suite uses ``delay``)."""
        return faultinject.fire("candidate")

    # -- burn-rate evaluation ---------------------------------------------
    def observe_candidate(self, endpoint: str,
                          latency_ms: Optional[float], error: bool,
                          trace_id: Optional[str] = None,
                          tenant: Optional[str] = None,
                          quality: Optional[Dict[str, float]] = None,
                          ) -> None:
        """One candidate outcome (shadow execute or live canary request).
        Feeds the candidate evaluators and runs the auto-action logic:
        short-window burn -> rollback; ``promote_after`` clean windows
        in canary -> promote.  ``quality`` carries the shadow
        comparison's signals (``divergence``/``agreement``/…), judged by
        the quality-kind evaluators with the same burn math — a
        fast-but-wrong candidate rolls back exactly like a slow one.  A
        tenant-scoped SLO judges only that tenant's outcomes, exactly
        like the primary-side ``SloManager.observe`` (tenantless shadow
        mirrors are skipped by tenant-scoped targets — they cannot be
        attributed)."""
        action = None
        with self._lock:
            cand = self._cand
            if cand is None:
                return
            now = self._clock()
            breach = None
            for ev in self._evaluators:
                slo = ev.slo
                if slo.endpoint is not None and slo.endpoint != endpoint:
                    continue
                if slo.tenant is not None and slo.tenant != tenant:
                    continue
                if slo.kind == "latency":
                    if latency_ms is None:
                        continue
                    bad = latency_ms > slo.threshold_ms
                elif slo.kind == "quality":
                    value = None if quality is None else \
                        quality.get(slo.metric)
                    if value is None:
                        continue  # no quality evidence this outcome
                    bad = (value < slo.threshold if slo.bad_below
                           else value > slo.threshold)
                else:
                    bad = error
                if bad and trace_id is not None:
                    self._offenders.append(trace_id)
                ev.observe(bad, trace_id)
                rates = ev.burn_rates()
                short = rates.get("short")
                if short is not None:
                    self.metrics.gauge(
                        self.metrics.labeled("deploy_candidate_burn_",
                                             _slug(slo.name)),
                        help="candidate short-window burn rate",
                    ).set(round(short, 3))
                if short is not None and short >= slo.burn_threshold:
                    breach = {"slo": slo.name, "burn_rate_short":
                              round(short, 3),
                              "burn_threshold": slo.burn_threshold}
            self._window_events += 1
            if breach is not None:
                self._window_breached = True
                action = ("rollback", breach)
            elif now - self._window_start >= self.window_s:
                if (self._window_events >= self.min_events
                        and not self._window_breached):
                    self._clean_windows += 1
                elif self._window_breached:
                    self._clean_windows = 0
                # a low-traffic window neither counts nor resets: clean
                # means "enough evidence and none of it bad"
                self._window_start = now
                self._window_events = 0
                self._window_breached = False
                self.metrics.gauge(
                    "deploy_clean_windows",
                    help="consecutive clean candidate burn windows",
                ).set(self._clean_windows)
                if (cand.phase == "canary"
                        and self._clean_windows >= self.promote_after):
                    action = ("promote", None)
        # auto actions run OUTSIDE the lock: they do forensics + HTTP
        if action is not None and action[0] == "rollback":
            self.rollback(reason="burn_rate", detail=action[1])
        elif action is not None:
            self.promote()

    # -- rollback evidence / fleet pin ------------------------------------
    def _capture_rollback(self, report: dict, offenders: List[str],
                          rates: Dict[str, dict]) -> None:
        engine = self.engine
        detail = dict(report)
        detail["trace_ids"] = offenders[-20:][::-1]
        detail["burn_rates"] = rates
        if engine._forensics is None:
            return
        if not engine._triggers.fire(TRIGGER_DEPLOY_ROLLBACK,
                                     engine.request_count):
            return
        extra = None
        if offenders:
            traces = {
                tid: [s.to_dict() for s in engine.tracer.sink.trace(tid)]
                for tid in detail["trace_ids"]
            }
            extra = {"deploy_traces.json": {
                k: v for k, v in traces.items() if v}}
        path = engine._forensics.capture(
            TRIGGER_DEPLOY_ROLLBACK, engine.request_count, detail,
            trace=False, extra_files=extra,
        )
        if path is None:
            engine._triggers.refund(TRIGGER_DEPLOY_ROLLBACK,
                                    engine.request_count)

    def _pin_fleet(self, step: int) -> dict:
        """Converge every replica onto ``step`` through the router's
        two-phase rollout (PR 7 semantics: stage everywhere, gate, drain,
        commit — or all-revert).  A fleet already serving ``step``
        reports ``noop``, which is success for a pin."""
        if self.pin_url is None:
            return {"ok": True, "skipped": "no pin_url"}
        http = self._http if self._http is not None else _default_pin_http
        try:
            status, body = http(
                f"{self.pin_url}/rollout",
                json.dumps({"step": int(step)}).encode(),
                self.pin_timeout_s,
            )
            payload = json.loads(body) if body else {}
            ok = status == 200 and payload.get("status") in (
                "committed", "noop")
            if not ok:
                self.metrics.counter(
                    "deploy_pin_failures",
                    help="fleet pin rollouts that did not commit",
                ).inc()
            return {"ok": ok, "status": payload.get("status"),
                    "http_status": status, "step": int(step)}
        except Exception as e:  # glomlint: disable=conc-broad-except -- the pin outcome (incl. an unreachable router) is recorded in the rollback/promote report; the deploy state flip must never be lost to a transport error
            self.metrics.counter(
                "deploy_pin_failures",
                help="fleet pin rollouts that did not commit",
            ).inc()
            return {"ok": False, "error": f"{type(e).__name__}: {e}",
                    "step": int(step)}


def _default_pin_http(url: str, body: bytes, timeout: float):
    import urllib.request

    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _slug(name: str) -> str:
    import re

    return re.sub(r"[^a-zA-Z0-9_]", "_", name)
