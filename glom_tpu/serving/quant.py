"""Reduced-precision serving variants: int8 weights / bf16 activations.

Serving a frozen checkpoint is weight-bandwidth-bound long before it is
FLOP-bound (the Gemma-on-TPU study, arXiv:2605.25645: once batching and
AOT compilation are in place, reduced-precision inference is the dominant
remaining lever).  Three quant modes, selected per engine:

  * ``f32``  — the checkpoint's native dtype; the accuracy reference.
  * ``bf16`` — every float leaf cast to bf16 HOST-SIDE (half the HBM
    footprint and half the weight-fetch bandwidth; an in-graph cast would
    keep f32 in HBM) and bf16 compute.
  * ``int8`` — weight-only symmetric per-output-channel int8: matrix
    leaves are stored as ``{"int8_q": int8, "int8_scale": f32}`` and
    dequantized IN-GRAPH to bf16 right before the matmul (XLA fuses the
    dequant into the weight read, so HBM traffic is 1 byte/weight);
    activations run bf16.  Vectors (biases, pos/init embeddings) stay
    bf16 — they are bandwidth-trivial and quantizing them costs accuracy
    for nothing.

``accuracy_report`` is the bit-accuracy harness contract
(``tools/quant_check.py``): per-level cosine / max-abs error of each
quant mode against the f32 reference on the two serving endpoints.  The
documented acceptance thresholds live in :data:`ACCURACY_THRESHOLDS`;
a mode that misses them must not be deployed (the harness exits
nonzero).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

QUANT_MODES = ("f32", "bf16", "int8")

# Acceptance thresholds of the bit-accuracy harness, per quant mode:
# cosine similarity vs the f32 reference (per level for /embed, whole
# tensor for /reconstruct) must be >= `cosine`, and the max abs error
# normalized by the f32 output's abs max must be <= `max_abs_rel`.
# Calibrated on the demo + tiny configs with ~4x margin over measured
# error (int8 measured ~0.9999 cosine / ~0.01 rel; bf16 tighter) —
# tools/quant_check.py enforces them, tests/test_quant.py pins them.
ACCURACY_THRESHOLDS = {
    "bf16": {"cosine": 0.995, "max_abs_rel": 0.05},
    "int8": {"cosine": 0.99, "max_abs_rel": 0.10},
}

_QKEY, _SKEY = "int8_q", "int8_scale"


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and _QKEY in x and _SKEY in x


def _quantize_leaf_int8(w: jax.Array) -> dict:
    """Symmetric per-output-channel int8: scale over the input-feature
    axis only (axis -2), so each output channel keeps its own dynamic
    range AND leading group axes stay independent — the grouped
    ``(L, d, h)`` nets get a per-(level, channel) ``(L, 1, h)`` scale
    rather than one range shared across all level nets (a level whose
    weights sit 10x lower than another's must not quantize to a handful
    of codes)."""
    w32 = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = (amax / 127.0 + np.float32(amax == 0.0)).astype(np.float32)
    q = np.clip(np.rint(w32 / scale), -127, 127).astype(np.int8)
    return {_QKEY: q, _SKEY: scale}


# every matmul weight in this model's param trees sits under one of these
# dict keys (decoder/patch_embed "w", the grouped nets' "w1"/"w2");
# biases and the pos/init embeddings deliberately never match
_MATMUL_KEYS = frozenset({"w", "w1", "w2"})


def quantize_tree(params, mode: str):
    """Host-side quantization of a parameter pytree for serving.

    ``f32`` returns the tree unchanged; ``bf16`` casts float leaves;
    ``int8`` replaces matmul WEIGHT leaves (dict key ``w``/``w1``/``w2``
    — shape alone would also catch pos_emb/init_levels, whose error lands
    verbatim in activations instead of washing through a matmul) with
    ``{"int8_q", "int8_scale"}`` records and casts the rest to bf16.
    The result round-trips through ``jax.device_put`` and
    ``ShapeDtypeStruct`` tree_maps like any pytree."""
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quant mode {mode!r}; one of {QUANT_MODES}")
    if mode == "f32":
        return params

    def one(path, leaf):
        arr = np.asarray(leaf)
        # jnp.issubdtype, not np: a bf16-param checkpoint's ml_dtypes
        # leaves are floating to jax but not to numpy — np's check would
        # silently pass every leaf through unquantized
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            return leaf
        key = getattr(path[-1], "key", None) if path else None
        if mode == "int8" and arr.ndim >= 2 and key in _MATMUL_KEYS:
            return _quantize_leaf_int8(arr)
        return arr.astype(jnp.bfloat16)

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_tree(params):
    """In-graph inverse: int8 records become bf16 weights (product taken
    in f32, then cast — one rounding, fused by XLA into the weight read);
    everything else passes through.  Identity for f32/bf16 trees."""

    def one(leaf):
        if _is_qleaf(leaf):
            return (leaf[_QKEY].astype(jnp.float32) * leaf[_SKEY]).astype(
                jnp.bfloat16
            )
        return leaf

    return jax.tree_util.tree_map(one, params, is_leaf=_is_qleaf)


def serving_config(config, mode: str):
    """The model config a quantized engine compiles against: bf16 compute
    for the reduced-precision modes, untouched for f32."""
    if mode == "f32":
        return config
    return dataclasses.replace(config, compute_dtype=jnp.bfloat16)


def quantized_forward(fn, mode: str):
    """Wrap an endpoint forward ``fn(params, imgs, *rest)`` so it accepts
    the quantized tree: dequantization happens INSIDE the traced graph
    (the whole point — the executable's weight inputs stay int8/bf16).
    Extra positional args (the stateful session forwards' carried
    ``levels``) pass through untouched — state is activations, never
    weights, and must not be quantized."""
    if mode == "f32":
        return fn

    def f(qparams, imgs, *rest):
        return fn(dequantize_tree(qparams), imgs, *rest)

    return f


# ---------------------------------------------------------------------------
# bit-accuracy harness core (tools/quant_check.py is the CLI)
# ---------------------------------------------------------------------------


def _cosine(a: np.ndarray, b: np.ndarray) -> float:
    a = a.astype(np.float64).ravel()
    b = b.astype(np.float64).ravel()
    denom = float(np.linalg.norm(a) * np.linalg.norm(b))
    return float(a @ b / denom) if denom else 1.0


def _errors(ref: np.ndarray, got: np.ndarray) -> Dict[str, float]:
    ref = np.asarray(ref, np.float32)
    got = np.asarray(got, np.float32)
    scale = float(np.max(np.abs(ref))) or 1.0
    return {
        "cosine": round(_cosine(ref, got), 6),
        "max_abs": round(float(np.max(np.abs(ref - got))), 6),
        "max_abs_rel": round(float(np.max(np.abs(ref - got))) / scale, 6),
    }


def accuracy_report(config, train_cfg, params, imgs,
                    modes=("bf16", "int8"), *, iters: Optional[int] = None):
    """Run each quant mode against the f32 reference on both serving
    endpoints; returns ``{mode: {"embed": {...per-level + overall...},
    "reconstruct": {...}, "pass": bool}}``.  Per-level rows for /embed —
    GLOM's levels are the product being served, and quantization error
    concentrates in the upper levels (more matmuls deep)."""
    from glom_tpu.serving.engine import _make_embed_fn, _make_reconstruct_fn

    def run(mode):
        cfg = serving_config(config, mode)
        qp = jax.device_put(quantize_tree(params, mode))
        embed = jax.jit(quantized_forward(_make_embed_fn(cfg, iters), mode))  # glomlint: disable=jax-request-path-compile -- offline accuracy harness (tools/quant_check), never reached by the serving request path
        recon = jax.jit(  # glomlint: disable=jax-request-path-compile -- offline accuracy harness (tools/quant_check), never reached by the serving request path
            quantized_forward(_make_reconstruct_fn(cfg, train_cfg, iters), mode)
        )
        return np.asarray(embed(qp, imgs)), np.asarray(recon(qp, imgs))

    ref_embed, ref_recon = run("f32")
    report = {}
    for mode in modes:
        if mode == "f32":
            continue
        got_embed, got_recon = run(mode)
        levels = {
            f"level_{l}": _errors(ref_embed[:, l], got_embed[:, l])
            for l in range(ref_embed.shape[1])
        }
        embed_err = _errors(ref_embed, got_embed)
        recon_err = _errors(ref_recon, got_recon)
        thr = ACCURACY_THRESHOLDS[mode]
        worst_cos = min(
            [embed_err["cosine"], recon_err["cosine"]]
            + [v["cosine"] for v in levels.values()]
        )
        # per-level rows participate like they do in worst_cos: each level
        # normalizes by its OWN abs-max, so a degraded upper level cannot
        # hide behind the whole-tensor scale (dominated by level 0)
        worst_rel = max(
            [embed_err["max_abs_rel"], recon_err["max_abs_rel"]]
            + [v["max_abs_rel"] for v in levels.values()]
        )
        report[mode] = {
            "embed": {"overall": embed_err, **levels},
            "reconstruct": recon_err,
            "thresholds": dict(thr),
            "worst_cosine": round(worst_cos, 6),
            "worst_max_abs_rel": round(worst_rel, 6),
            "pass": bool(worst_cos >= thr["cosine"]
                         and worst_rel <= thr["max_abs_rel"]),
        }
    return report
