"""Serving engine: model lifecycle + batched execution.

Ties the request path together:

  request -> :class:`~glom_tpu.serving.batcher.DynamicBatcher` (one per
  endpoint) -> worker thread -> bucket-padded AOT executable
  (:class:`~glom_tpu.serving.compile_cache.BucketedCompileCache`) ->
  sliced per-request results resolved onto the callers' futures.

Model lifecycle:

  * **load** — params come from the newest finalized checkpoint
    (``checkpoint.latest_step`` + the shared
    ``training.denoise.load_checkpoint_state`` read path), templates are
    built once and reused for every later reload;
  * **hot reload** — a watcher polls ``latest_step`` on a timer; when a
    newer step lands, the new params are restored OFF the request path and
    swapped in atomically (one reference assignment).  In-flight batches
    captured the old reference before the swap and finish on the old
    params — no request ever sees a half-updated tree.  A reload that
    fails (half-written artifact, torn manifest, shape drift) warns and
    keeps serving the old params;
  * **staged reload** — the fleet-coordination primitive
    (:meth:`ServingEngine.stage_reload` / :meth:`commit_staged` /
    :meth:`abort_staged` / :meth:`rollback`, exposed over HTTP as
    ``/admin/reload/*``): a router rolls N replicas forward in two
    phases so the fleet is never half-old/half-new, and a failed commit
    anywhere reverts everyone (:mod:`glom_tpu.serving.router`);
  * **drain** — :meth:`ServingEngine.shutdown` with ``drain=True`` (the
    server's SIGTERM path, mirroring the trainer's preemption handling)
    stops admission, lets queued work flush, and joins the workers before
    returning.

With a ``mesh_shape``, every bucket AOT-compiles against explicit in/out
shardings and the params are placed per the training-side rules
(:mod:`glom_tpu.serving.sharded`) — TP/EP-sharded configs serve from the
proven ``parallel/`` stack with the same zero-request-path-compile
contract.

Observability rides the existing ``glom_tpu.obs`` registry: latency
histograms, queue-depth / batch-occupancy metrics, shed + compile + reload
counters — all visible through the server's ``/metrics`` endpoint.  A
:class:`~glom_tpu.obs.triggers.QueueSaturationMonitor` watches sustained
overload and, gated by the shared
:class:`~glom_tpu.obs.triggers.TriggerEngine`, dumps a forensics bundle
exactly like the trainer's anomaly path.

Every request is traced end-to-end (:mod:`glom_tpu.obs.tracing`): the
server mints the request span, the batcher/executor record queue-wait,
assembly, pad, and execute spans under it, ``reload_swap`` spans time the
hot-reload path, and ``trace_log`` emits one JSONL record per completed
trace.  Declarative SLOs (``slos``; :mod:`glom_tpu.obs.slo`) evaluate
request outcomes with multi-window burn-rate math and fire the
``slo_burn`` trigger into a forensics bundle naming the offending trace
IDs.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from glom_tpu import checkpoint as ckpt_lib
from glom_tpu.config import GlomConfig, TrainConfig
from glom_tpu.models import glom as glom_model
from glom_tpu.models.heads import decoder_apply
from glom_tpu.obs import MetricRegistry
from glom_tpu.obs import attribution as obs_attribution
from glom_tpu.obs.events import Timeline
from glom_tpu.obs.forensics import ForensicsManager
from glom_tpu.obs.quality import QualityPlane, make_quality_fn, unpack_signals
from glom_tpu.obs.slo import SLO, SloManager, parse_slo
from glom_tpu.obs.tracing import (
    SPAN_BATCH_ASSEMBLY,
    SPAN_RELOAD,
    TraceSink,
    Tracer,
)
from glom_tpu.obs.triggers import (
    TRIGGER_QUEUE_SATURATION,
    QueueSaturationMonitor,
    TriggerEngine,
)
from glom_tpu.resilience import faultinject, integrity
from glom_tpu.serving import quant as serving_quant
from glom_tpu.serving import sessions as serving_sessions
from glom_tpu.serving.batcher import (  # noqa: F401
    Closed,
    DynamicBatcher,
    Overloaded,
    TenantQuotaExceeded,
)
from glom_tpu.hierarchy import parse as hierarchy_parse
from glom_tpu.serving.compile_cache import BucketedCompileCache, PostPassCache
from glom_tpu.training import denoise

ENDPOINTS = ("embed", "reconstruct", "parse")
# endpoints an SLO may target: the batched stateless trio plus the
# session (stateful streaming) and similar (index-query) paths, which
# have no batcher but the same outcome-observation contract
SLO_ENDPOINTS = ENDPOINTS + ("session", "similar")

DEMO_CONFIG = GlomConfig(dim=16, levels=3, image_size=16, patch_size=8)


def make_demo_checkpoint(directory: str, *, config: Optional[GlomConfig] = None,
                         train: Optional[TrainConfig] = None, seed: int = 0) -> int:
    """Write a tiny untrained-but-servable checkpoint (step 0) in the
    Trainer's self-describing layout — the zero-setup path for smoke tests
    and ``tools/loadgen.py --smoke``.  Returns the step written."""
    import json
    import os

    import optax

    config = config if config is not None else DEMO_CONFIG
    train = train if train is not None else TrainConfig(batch_size=2, steps=0)
    state = denoise.init_state(
        jax.random.PRNGKey(seed), config, optax.sgd(0.0),
        decoder=train.decoder, decoder_hidden_mult=train.decoder_hidden_mult,
    )
    os.makedirs(directory, exist_ok=True)
    payload = json.dumps(
        {"glom": config.to_json_dict(), "train": train.to_json_dict()},
        indent=2,
    ).encode()
    ckpt_lib._atomic_write(directory, "config.json", lambda f: f.write(payload))
    ckpt_lib.save(directory, 0, {"params": jax.device_get(state.params)})
    return 0


def _make_embed_fn(config: GlomConfig, iters: Optional[int],
                   *, ff_fn=None, fused_fn=None):
    """``(params, imgs) -> (b, L, d)`` mean-pooled per-level embeddings —
    the per-level artifact GLOM exposes downstream (PAPER.md levels;
    ``training/extract.py``'s pooling, compiled for serving).  All levels
    are always computed; the endpoint slices one host-side, so one compiled
    graph per bucket serves every ``level=`` query.  ``ff_fn``/``fused_fn``
    are the mesh-bound kernels a sharded engine injects
    (:func:`glom_tpu.serving.sharded.resolve_sharded_kernels`)."""

    def f(params, imgs):
        out = glom_model.apply(params["glom"], imgs, config=config,
                               iters=iters, ff_fn=ff_fn, fused_fn=fused_fn)
        return jnp.mean(out, axis=1)

    return f


def _make_reconstruct_fn(config: GlomConfig, train_cfg: TrainConfig,
                         iters: Optional[int], *, ff_fn=None, fused_fn=None):
    """``(params, imgs) -> (b, c, H, W)`` denoising forward: the state at
    the TRAINING loss timestep decoded through the trained head — the
    decode path the decoder was optimized for, not an arbitrary final-state
    decode."""
    resolved_iters = iters if iters is not None else (
        train_cfg.iters if train_cfg.iters is not None else config.default_iters
    )
    timestep = denoise.resolve_loss_timestep(train_cfg, resolved_iters)

    def f(params, imgs):
        _, captured = glom_model.apply(
            params["glom"], imgs, config=config, iters=resolved_iters,
            capture_timestep=timestep, ff_fn=ff_fn, fused_fn=fused_fn,
        )
        return decoder_apply(
            params["decoder"], captured, config,
            arch=train_cfg.decoder, level=train_cfg.loss_level,
        )

    return f


def _make_session_fns(config: GlomConfig, cold_iters: int, warm_iters: int,
                      *, ff_fn=None, fused_fn=None):
    """The stateful (streaming) forwards — ``models/video.py``'s
    carried-levels semantics, split into the two request-path graphs:

      * ``cold(params, imgs) -> (emb, levels)`` — full settle from
        ``init_levels`` at ``cold_iters`` (a session's first frame, or a
        cold restart after eviction/failover);
      * ``warm(params, imgs, levels) -> (emb, levels)`` — warm-start from
        the previous frame's equilibrium at the reduced ``warm_iters``.

    Both return the final column state alongside the mean-pooled
    per-level embeddings, so ``k`` chained calls reproduce
    ``video.rollout`` over the same ``k`` frames exactly (same
    ``glom_model.apply``, same carried-levels dtype rule)."""

    def cold(params, imgs):
        levels = glom_model.apply(params["glom"], imgs, config=config,
                                  iters=cold_iters, ff_fn=ff_fn,
                                  fused_fn=fused_fn)
        return jnp.mean(levels, axis=1), levels

    def warm(params, imgs, levels):
        new = glom_model.apply(params["glom"], imgs, config=config,
                               iters=warm_iters, levels=levels,
                               ff_fn=ff_fn, fused_fn=fused_fn)
        return jnp.mean(new, axis=1), new

    return cold, warm


class ServingEngine:
    """One loaded model + per-endpoint batchers, workers, and caches.

    ``clock`` is injectable (tests drive batching deterministically);
    ``start(workers=False)`` skips the worker/watcher threads so tests can
    pump :meth:`process_once` by hand.
    """

    def __init__(
        self,
        checkpoint_dir: str,
        *,
        buckets: Sequence[int] = (1, 2, 4, 8),
        iters: Optional[int] = None,
        max_wait_ms: float = 5.0,
        max_queue: int = 64,
        registry: Optional[MetricRegistry] = None,
        reload_poll_s: float = 2.0,
        reload_retries: int = 3,
        reload_retry_base_s: float = 0.05,
        reload_backoff_max: int = 8,
        sleep=None,
        warmup: bool = True,
        warmup_dir: Optional[str] = None,
        forensics_dir: Optional[str] = None,
        saturation_threshold: float = 0.9,
        saturation_sustained: int = 3,
        saturation_debounce: int = 200,
        max_captures: int = 3,
        clock=None,
        trace_log: Optional[str] = None,
        trace_max_traces: int = 256,
        slos: Optional[Sequence] = None,
        quant: str = "f32",
        ff_impl: Optional[str] = None,
        donate_inputs: Optional[bool] = None,
        mesh_shape: Optional[Sequence[int]] = None,
        param_sharding: str = "replicated",
        mesh_axis_names: Sequence[str] = ("data", "model", "seq"),
        warm_iters=None,
        session_ttl_s: float = 600.0,
        session_max_bytes: int = 256 * 2 ** 20,
        session_spill_dir: Optional[str] = None,
        tenant_quotas: Optional[Dict[str, object]] = None,
        extra_models: Optional[Dict[str, str]] = None,
        deploy_promote_after: int = 3,
        deploy_window_s: Optional[float] = None,
        deploy_min_events: Optional[int] = None,
        deploy_canary_fraction: float = 0.1,
        deploy_pin_url: Optional[str] = None,
        capacity_policy: Optional[str] = None,
        capacity_interval_s: float = 1.0,
        capacity_window_s: float = 30.0,
        capacity_persist_windows: int = 5,
        capacity_ceiling: Optional[float] = None,
        quality_sample: float = 1.0,
        quality_seed: int = 0,
        bulk_dir: Optional[str] = None,
        parse_thresholds=None,
        index_dir: Optional[str] = None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.registry = registry if registry is not None else MetricRegistry()
        self._clock = clock if clock is not None else time.monotonic

        # -- end-to-end tracing (glom_tpu.obs.tracing) ---------------------
        # Always on: spans are host-side dict bookkeeping in a bounded
        # sink.  With trace_log set, every completed request trace is also
        # emitted as one JSONL record (tools/trace_report.py reads it).
        trace_exporter = None
        if trace_log:
            from glom_tpu.obs.exporters import JsonlExporter

            trace_exporter = JsonlExporter(path=trace_log)
        self.tracer = Tracer(
            clock=self._clock,
            sink=TraceSink(max_traces=trace_max_traces),
            registry=self.registry,
            exporter=trace_exporter,
        )
        self._reload_poll_s = reload_poll_s
        if reload_retries < 1:
            raise ValueError(f"reload_retries must be >= 1, got {reload_retries}")
        self._reload_retries = reload_retries
        self._reload_retry_base_s = reload_retry_base_s
        self._reload_backoff_max = max(1, reload_backoff_max)
        self._reload_failstreak = 0
        self._sleep = sleep if sleep is not None else time.sleep
        self._warmup_dir = warmup_dir
        # checkpoint-integrity telemetry (triggers/forensics attached below,
        # once they exist): corrupt artifacts found at load or reload time
        # are quarantined, counted, and ckpt_corrupt-triggered — the engine
        # serves the newest params that VERIFY instead of crashing
        self._integrity_obs = integrity.IntegrityObserver(registry=self.registry)

        if ckpt_lib.latest_step(checkpoint_dir) is None:
            raise FileNotFoundError(
                f"no finalized checkpoint in {checkpoint_dir!r} — the engine "
                f"needs a manifest to serve from (train first, or "
                f"make_demo_checkpoint for a smoke run)"
            )
        step, self.config, self.train_cfg, host_params = (
            denoise.load_checkpoint_state(
                checkpoint_dir, observer=self._integrity_obs,
            )
        )
        if ff_impl is not None:
            # serving-side kernel override: lets an operator turn the fused
            # single-launch level update (ff_impl='fused') on/off for a
            # checkpoint regardless of the config it trained under — the
            # weights are identical either way
            import dataclasses

            self.config = dataclasses.replace(self.config, ff_impl=ff_impl)
        # -- quantized serving (glom_tpu.serving.quant) --------------------
        # One engine serves ONE quant mode: the compile cache registers its
        # per-bucket entries under that label, and hot reload re-quantizes
        # every new checkpoint the same way.  The f32 host tree stays the
        # restore template; the device tree is the quantized one.
        if quant not in serving_quant.QUANT_MODES:
            raise ValueError(
                f"unknown quant mode {quant!r}; one of {serving_quant.QUANT_MODES}"
            )
        self.quant = quant
        serve_cfg = serving_quant.serving_config(self.config, quant)
        # template for every later reload: restore() places leaves onto the
        # template's dtypes/shardings, so reloads land where the originals did
        self._template = host_params

        # -- mesh-sharded execution (glom_tpu.serving.sharded) -------------
        # With a mesh_shape, every bucket AOT-compiles against explicit
        # in/out shardings: params placed per the training-side rules
        # (TP: FF hidden sharded; EP: whole level-nets), the batch over
        # the data axis — the proven parallel/ stack in the request path.
        self.param_sharding = param_sharding
        self.mesh = None
        param_sh = img_sh = out_sh = None
        ff_fn = fused_fn = None
        # quantize ONCE: the same host tree feeds the sharding-tree
        # derivation (shapes) and the device placement (values) — int8's
        # per-channel absmax pass over every weight must not run twice
        quantized = serving_quant.quantize_tree(host_params, quant)
        if mesh_shape is not None or param_sharding != "replicated":
            from glom_tpu.serving import sharded as serving_sharded

            if mesh_shape is None:
                raise ValueError(
                    f"param_sharding={param_sharding!r} needs a mesh_shape "
                    f"(e.g. (1, 4, 1) for 4-way TP)"
                )
            self.mesh = serving_sharded.resolve_mesh(mesh_shape,
                                                     mesh_axis_names)
            serving_sharded.validate_buckets(
                buckets, self.mesh, data_axis=mesh_axis_names[0])
            ff_fn, fused_fn = serving_sharded.resolve_sharded_kernels(
                self.mesh, serve_cfg, param_sharding=param_sharding,
                data_axis=mesh_axis_names[0], model_axis=mesh_axis_names[1],
                seq_axis=mesh_axis_names[2],
            )
            param_sh = serving_sharded.param_shardings(
                self.mesh, serve_cfg, quantized,
                param_sharding=param_sharding,
                model_axis=mesh_axis_names[1],
            )
            img_sh, out_sh = serving_sharded.batch_shardings(
                self.mesh, data_axis=mesh_axis_names[0])
        self._param_shardings = param_sh
        self._params = self._place(quantized)
        self.step = step
        self.iters = iters

        # -- compiled forward per endpoint ---------------------------------
        mesh_axes = None
        if self.mesh is not None:
            from glom_tpu.serving.sharded import mesh_axes_dict

            mesh_axes = mesh_axes_dict(self.mesh)
        shardings = (None if param_sh is None
                     else (param_sh, img_sh, out_sh))
        self.caches: Dict[str, BucketedCompileCache] = {
            "embed": BucketedCompileCache(
                serving_quant.quantized_forward(
                    _make_embed_fn(serve_cfg, iters,
                                   ff_fn=ff_fn, fused_fn=fused_fn), quant),
                buckets, name="embed", quant=quant, donate=donate_inputs,
                shardings=shardings, mesh_axes=mesh_axes),
            "reconstruct": BucketedCompileCache(
                serving_quant.quantized_forward(
                    _make_reconstruct_fn(serve_cfg, self.train_cfg, iters,
                                         ff_fn=ff_fn, fused_fn=fused_fn),
                    quant),
                buckets, name="reconstruct", quant=quant,
                donate=donate_inputs,
                shardings=shardings, mesh_axes=mesh_axes),
        }
        # -- part-whole workload plane (glom_tpu/hierarchy/) ---------------
        # The "index" cache is the bulk transform's forward (raw f32
        # column states) AND the /similar query embedder.  /parse is NOT
        # a second settle family: it rides the index executables plus an
        # AOT islanding post-pass (PostPassCache), so the plane costs
        # ~one compiled family at warmup, not three — and neither path
        # ever compiles on the request path.
        self.parse_thresholds = hierarchy_parse.parse_thresholds(
            parse_thresholds, serve_cfg.levels)
        self.caches["index"] = BucketedCompileCache(
            serving_quant.quantized_forward(
                hierarchy_parse.make_index_fn(
                    serve_cfg, iters, ff_fn=ff_fn, fused_fn=fused_fn),
                quant),
            buckets, name="index", quant=quant, donate=donate_inputs,
            shardings=shardings, mesh_axes=mesh_axes)
        c = serve_cfg
        self.caches["parse"] = PostPassCache(
            self.caches["index"],
            hierarchy_parse.make_pack_fn(serve_cfg, self.parse_thresholds),
            lambda b: jax.ShapeDtypeStruct(
                (b, c.num_patches, c.levels, c.dim), np.float32),
            name="parse", sharding=img_sh)
        self.index_dir = index_dir
        self._index = None
        if index_dir is not None:
            from glom_tpu.hierarchy.index import LevelIndex

            self._index = LevelIndex(index_dir, serve_cfg.levels)
        max_bucket = self.caches["embed"].max_bucket

        # -- stateful session serving (glom_tpu.serving.sessions) ----------
        # warm_iters enables it: a per-session column-state cache plus two
        # extra compile-cache entries per bucket — the (batch, stateful)
        # bucket matrix.  Cold settles from init_levels at the full
        # iteration count; warm starts from the previous frame's
        # equilibrium at warm_iters (video.rollout's carried-levels
        # semantics, AOT-compiled so levels-in/levels-out signatures never
        # compile on the request path).
        self._session_cold_iters = int(
            iters if iters is not None else self.config.default_iters)
        self.sessions: Optional[serving_sessions.SessionStore] = None
        self._session_spill_dir = session_spill_dir
        self._state_sharding = img_sh  # leading-axis spec: rank-agnostic
        if warm_iters is not None:
            if warm_iters == "auto":
                warm_iters = max(1, self._session_cold_iters // 2)
            warm_iters = int(warm_iters)
            if warm_iters < 1:
                raise ValueError(f"warm_iters must be >= 1, got {warm_iters}")
            self._session_warm_iters = warm_iters
            cold_fn, warm_fn = _make_session_fns(
                serve_cfg, self._session_cold_iters, warm_iters,
                ff_fn=ff_fn, fused_fn=fused_fn,
            )
            self.caches["session_cold"] = BucketedCompileCache(
                serving_quant.quantized_forward(cold_fn, quant),
                buckets, name="session_cold", quant=quant,
                donate=donate_inputs, shardings=shardings,
                mesh_axes=mesh_axes, carries_state=True,
                iters=self._session_cold_iters)
            self.caches["session_warm"] = BucketedCompileCache(
                serving_quant.quantized_forward(warm_fn, quant),
                buckets, name="session_warm", quant=quant,
                donate=donate_inputs, shardings=shardings,
                mesh_axes=mesh_axes, carries_state=True, takes_state=True,
                state_sharding=img_sh, iters=warm_iters)
            # /session/parse rides the SAME column state as
            # /session/embed (one equilibrium per session — the two
            # frame kinds interleave freely) AND the same executables:
            # a parse frame runs the embed pair's (batch, stateful)
            # entry, then the islanding post-pass on the carried state
            # (warm() admits the state dtype's avals into the parse
            # PostPassCache) — no extra settle families to compile.
            # The carried-state aval: what apply() returns under the
            # serving config (compute dtype; quantized trees dequantize
            # in-graph and never change the activation dtype)
            c = serve_cfg
            self._state_dtype = jnp.dtype(c.compute_dtype or c.param_dtype)
            self._state_tail = (c.num_patches, c.levels, c.dim)
            self.sessions = serving_sessions.SessionStore(
                max_bytes=session_max_bytes, ttl_s=session_ttl_s,
                registry=self.registry, clock=self._clock,
            )
            if session_spill_dir:
                # warm-boot: a drained replica's spilled states come back
                # resident, so the fleet survives a reload without every
                # client paying a cold re-settle (serving_session_restores
                # counts what came back; invalid entries are dropped)
                self.sessions.restore(
                    session_spill_dir,
                    validate=self._valid_spilled_state,
                    place=self._place_state,
                )
        else:
            self._session_warm_iters = None

        # -- batchers (admission control) ----------------------------------
        self.batchers: Dict[str, DynamicBatcher] = {
            ep: DynamicBatcher(
                max_batch=max_bucket, max_wait_ms=max_wait_ms,
                max_queue=max_queue, clock=self._clock, tracer=self.tracer,
            )
            for ep in ENDPOINTS
        }

        # -- overload forensics --------------------------------------------
        # per endpoint: each endpoint has its own queue, and observations
        # of one must not reset (or double-count sheds into) the other's
        # saturation streak
        self._saturation = {
            ep: QueueSaturationMonitor(
                threshold=saturation_threshold, sustained=saturation_sustained,
            )
            for ep in ENDPOINTS
        }
        self._triggers = TriggerEngine(
            debounce_steps=saturation_debounce, max_captures=max_captures,
            registry=self.registry,
        )
        # -- unified event timeline (glom_tpu.obs.events) ------------------
        # One typed ring for every engine-side state transition: deploy
        # phase changes, capacity-advisor recommendations, bulk job
        # activity.  Served at GET /debug/timeline (role "engine") and
        # joined by the attribution plane against the TSDB-lite series.
        self.timeline = Timeline(clock=self._clock)

        self._forensics: Optional[ForensicsManager] = None
        if forensics_dir:
            # snapshot_fn reuses the warmup record for the largest bucket —
            # an overload capture must never pay (or risk) a compile
            self._forensics = ForensicsManager(
                forensics_dir,
                config={"checkpoint_dir": checkpoint_dir,
                        "buckets": list(self.caches["embed"].buckets),
                        "max_queue": max_queue, "max_wait_ms": max_wait_ms,
                        "glom": self.config.to_json_dict()},
                snapshot_fn=lambda: self.caches["embed"].snapshots.get(max_bucket),
                registry=self.registry,
                attribution_fn=lambda: obs_attribution.attribute(
                    obs_attribution.collect_engine_evidence(self)),
            )
        # now that triggers/forensics exist, give quarantine events the
        # full pipeline (debounced ckpt_corrupt trigger -> bundle)
        self._integrity_obs.triggers = self._triggers
        self._integrity_obs.forensics = self._forensics

        # -- SLO burn-rate alerting (glom_tpu.obs.slo) ---------------------
        # Declarative targets ("embed:p95<250ms", "errors<1%" or SLO
        # objects); burn fires the shared TriggerEngine's slo_burn trigger
        # into a forensics bundle naming the offending trace IDs.
        self._slo: Optional[SloManager] = None
        self._slo_lock = threading.Lock()
        if slos:
            parsed = [s if isinstance(s, SLO) else parse_slo(s) for s in slos]
            for s in parsed:
                # fail loud at startup: a typoed endpoint would be
                # accepted and then silently never evaluate — the worst
                # failure mode for an alerting layer
                if s.endpoint is not None and s.endpoint not in SLO_ENDPOINTS:
                    raise ValueError(
                        f"SLO {s.name!r} names unknown endpoint "
                        f"{s.endpoint!r}; valid endpoints: {SLO_ENDPOINTS}"
                    )
            self._slo = SloManager(
                parsed,
                clock=self._clock, registry=self.registry,
                triggers=self._triggers, forensics=self._forensics,
                tracer=self.tracer,
            )

        # -- model-quality telemetry (glom_tpu.obs.quality) ----------------
        # A jitted post-pass (island agreement / entropy / norms /
        # reconstruction residual) attached HERE, outside the compile
        # cache module, as one more AOT-warmed bucketed executable — the
        # request path never compiles for quality.  Sampling is the PR 9
        # credit accumulator; signals feed bounded mergeable sketches and
        # the quality-kind SLOs.  quality_sample <= 0 skips the extra
        # executable entirely (the plane still exists; it just never
        # samples).
        self.quality_cache: Optional[BucketedCompileCache] = None
        if quality_sample > 0:
            self.quality_cache = BucketedCompileCache(
                serving_quant.quantized_forward(
                    make_quality_fn(serve_cfg, self.train_cfg, iters,
                                    ff_fn=ff_fn, fused_fn=fused_fn), quant),
                buckets, name="quality", quant=quant,
                shardings=shardings, mesh_axes=mesh_axes)
        self.quality = QualityPlane(
            self.registry, levels=serve_cfg.levels,
            sample=quality_sample, seed=quality_seed, clock=self._clock)
        # the reference profile rides checkpoint conventions: adopt
        # quality_ref.json beside the checkpoints when one was captured
        self.quality.load_reference(checkpoint_dir)

        # -- model registry (glom_tpu.serving.registry) --------------------
        # Every servable (model, step) is a registry record; the startup
        # tree is the default model's primary, kept in sync by every
        # param-swap path.  A deploy candidate or an extra model is just
        # another resident record the partitioned execute can target.
        from glom_tpu.serving import registry as model_registry

        self.models = model_registry.ModelRegistry(
            registry=self.registry, clock=self._clock)
        self._signature = model_registry.cache_signature(
            self.config, quant, buckets, iters=iters, mesh_axes=mesh_axes)
        self.models.register(
            model_registry.DEFAULT_MODEL, step, params=self._params,
            caches=self.caches, config=serve_cfg, train_cfg=self.train_cfg,
            signature=self._signature, source_dir=checkpoint_dir,
            quant=quant, role="primary",
        )
        for name, model_dir in (extra_models or {}).items():
            if name == model_registry.DEFAULT_MODEL:
                raise ValueError(
                    f"extra model name {name!r} collides with the "
                    f"engine's own model")
            model_registry.load_version(
                name, model_dir, buckets=buckets, quant=quant, iters=iters,
                donate=donate_inputs, warmup=warmup, models=self.models,
                role="primary",
            )

        # -- tenant bulkheads (glom_tpu.serving.batcher) -------------------
        # One TenantAdmission shared across endpoints: a tenant's quota
        # is a promise about the tenant, not one queue.  Tenants without
        # a configured quota ride the global max_queue bound only.
        from glom_tpu.serving.batcher import TenantAdmission

        self.tenants: Optional[TenantAdmission] = (
            TenantAdmission(tenant_quotas, clock=self._clock)
            if tenant_quotas else None)

        # -- shadow/canary deploys (glom_tpu.serving.deploy) ---------------
        from glom_tpu.serving.deploy import DeployController

        self.deploy = DeployController(
            self, promote_after=deploy_promote_after,
            window_s=deploy_window_s, min_events=deploy_min_events,
            canary_fraction=deploy_canary_fraction, pin_url=deploy_pin_url,
        )

        # -- capacity plane (glom_tpu.obs.capacity) ------------------------
        # Always constructed (the TSDB + advisor are host-side dict work);
        # nothing samples until tick() is driven — the server main() and
        # the capacity smoke start the timer thread, tests tick under a
        # fake clock.  Recommendations are DRY-RUN by contract: the plane
        # can fire the debounced capacity_pressure trigger into forensics
        # but never touches admission, batching, or the fleet.
        from glom_tpu.obs.capacity import DEFAULT_POLICY, CapacityPlane

        self.capacity = CapacityPlane(
            self.registry,
            policy=capacity_policy or DEFAULT_POLICY,
            ceiling_imgs_per_sec=capacity_ceiling,
            interval_s=capacity_interval_s,
            window_s=capacity_window_s,
            persist_windows=capacity_persist_windows,
            clock=self._clock,
            triggers=self._triggers,
            forensics=self._forensics,
            tenants_fn=(lambda: self.tenants.snapshot()
                        if self.tenants is not None else None),
            on_recommend=lambda rec: self.timeline.note(
                "capacity_recommendation", action=rec["action"],
                reasons=rec.get("reasons", []),
                persisted=rec.get("persisted", 0)),
        )

        # -- bulk inference tier (glom_tpu.serving.bulk) -------------------
        # Scavenger-class offline jobs: with a bulk_dir the runner adopts
        # every unfinished job in that store on construction (resume after
        # a kill is zero-touch), fills residual bucket padding from
        # process_once, and runs idle-window buckets from its own thread
        # (started with the workers).  Bulk work rides the warmed
        # executables and never touches admission, quotas, or SLOs.
        self.bulk = None
        if bulk_dir is not None:
            from glom_tpu.serving.bulk import BulkRunner

            self.bulk = BulkRunner(self, bulk_dir, clock=self._clock)

        # -- staged (two-phase) reload state -------------------------------
        # ``_staged`` holds (step, placed-params) loaded by stage_reload()
        # but not yet serving; ``_prev`` holds the (step, params) a commit
        # displaced, so a fleet coordinator can roll THIS replica back if
        # a sibling's commit fails.  Guarded by ``_reload_lock`` — stage/
        # commit/abort/rollback arrive on router admin threads and must
        # not interleave.
        self._staged: Optional[tuple] = None
        self._prev: Optional[tuple] = None
        self._reload_lock = threading.Lock()

        self._lock = threading.Lock()  # params swap + counters + saturation
        # session-frame drain accounting: /session/* bypasses the
        # batchers, so shutdown needs its own barrier to know every
        # acknowledged frame's state has been put before the spill
        self._session_inflight = 0
        self._session_cv = threading.Condition()
        # /session/parse delta baselines (see _note_parse_labels)
        self._parse_labels: Dict[str, np.ndarray] = {}
        self._threads: list = []
        self._stop = threading.Event()
        self._started = False
        self._shed_seen = {ep: 0 for ep in ENDPOINTS}
        self.request_count = 0  # the serving analogue of the trainer's step

        if warmup:
            self.warm()

    # -- warmup ------------------------------------------------------------
    def warm(self) -> None:
        """AOT-compile every (endpoint, bucket) pair and record the per-
        bucket compile snapshots (written under ``warmup_dir`` when set).
        The request path never compiles after this returns."""
        c = self.config
        t0 = self._clock()
        for ep, cache in self.caches.items():
            if cache.warmed:
                continue
            # float32 MUST match what submit() feeds the executables (AOT
            # calls are aval-strict — a bf16-compiled executable given f32
            # images raises, it doesn't cast); the model itself casts to
            # its compute dtype in-graph (glom.cast_for_compute)
            cache.warmup(
                # glomlint: disable=conc-unguarded-attr -- warmup runs at startup / under the reload lock of the staged path; the watcher that swaps _params is not polling yet
                self._params,
                lambda b: jax.ShapeDtypeStruct(
                    (b, c.channels, c.image_size, c.image_size), np.float32,
                ),
                # the warm (takes_state) session cache additionally needs
                # the carried-state aval per bucket — this is what makes
                # the (batch, stateful) matrix fully AOT: a session's
                # levels-in/levels-out signature never compiles on the
                # request path
                state_struct_fn=(self._session_state_struct
                                 if cache.takes_state else None),
            )
            if self._warmup_dir:
                self._write_warmup_snapshots(ep, cache)
        if self.sessions is not None:
            # /session/parse = the session executables (warmed above) +
            # the islanding post-pass on the carried state — admit the
            # state dtype's avals so a parse frame never compiles
            for bucket in self.caches["session_cold"].buckets:
                self.caches["parse"].warm_aval(
                    self._session_state_struct(bucket))
        if self.quality_cache is not None and not self.quality_cache.warmed:
            # the quality post-pass warms per bucket alongside the
            # endpoint matrix: sampled batches hit already-compiled
            # executables, so quality telemetry costs zero request-path
            # compiles (poll_quality_compiles() keeps the counter honest)
            self.quality_cache.warmup(
                # glomlint: disable=conc-unguarded-attr -- warmup runs at startup / under the reload lock of the staged path; the watcher that swaps _params is not polling yet
                self._params,
                lambda b: jax.ShapeDtypeStruct(
                    (b, c.channels, c.image_size, c.image_size), np.float32,
                ),
            )
            if self._warmup_dir:
                self._write_warmup_snapshots("quality", self.quality_cache)
        self.registry.gauge(
            "serving_warmup_seconds",
            help="wall time of the startup AOT compile pass", unit="seconds",
        ).set(self._clock() - t0)

    def _write_warmup_snapshots(self, endpoint: str, cache) -> None:
        from glom_tpu.obs.forensics import write_bundle

        for bucket, snap in cache.snapshots.items():
            files = {"manifest.json": {
                "endpoint": endpoint, "bucket": bucket, "quant": cache.quant,
                "cost_analysis": snap.get("cost_analysis", {}),
                "memory_analysis": snap.get("memory_analysis", {}),
            }}
            if snap.get("hlo"):
                files["hlo.txt"] = snap["hlo"]
            try:
                write_bundle(self._warmup_dir, f"{endpoint}-b{bucket}", files)
            except OSError as e:
                warnings.warn(f"warmup snapshot write failed ({e})", stacklevel=2)

    # -- lifecycle ---------------------------------------------------------
    @property
    def params(self):
        # glomlint: disable=conc-unguarded-attr -- reference read is atomic under the GIL; reloads rebind the whole tree (the documented in-flight-on-old-params contract)
        return self._params

    def _place(self, quantized_tree):
        """Put a quantized host tree on device(s) — sharded per the mesh
        placement when one exists, default single-device otherwise.  The
        ONE placement call shared by startup, hot reload, and staged
        reloads, so a reload can never land in a different layout than
        the executables were compiled against."""
        if self._param_shardings is not None:
            return jax.device_put(quantized_tree, self._param_shardings)
        return jax.device_put(quantized_tree)

    def start(self, *, workers: bool = True, watch: Optional[bool] = None) -> None:
        """Spin up one worker thread per endpoint plus the hot-reload
        watcher (``watch`` defaults to ``reload_poll_s > 0``).  Tests pass
        ``workers=False`` and pump :meth:`process_once` / call
        :meth:`check_reload` directly."""
        if self._started:
            return
        self._started = True
        if watch is None:
            watch = self._reload_poll_s > 0
        if workers:
            for ep in ENDPOINTS:
                t = threading.Thread(
                    target=self._worker_loop, args=(ep,),
                    name=f"glom-serving-{ep}", daemon=True,
                )
                t.start()
                self._threads.append(t)
        if watch:
            t = threading.Thread(
                target=self._watch_loop, name="glom-serving-reload", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if workers and self.bulk is not None:
            # idle-window scavenging needs live workers to preempt it;
            # workers=False tests drive bulk.run_idle_once() by hand
            self.bulk.start()

    def shutdown(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful stop (the server's SIGTERM path): close admission,
        drain queued work (``drain=True``) or fail it fast, stop the
        watcher, join the threads.  Idempotent."""
        for batcher in self.batchers.values():
            batcher.close(drain=drain)
        self._stop.set()
        if self.bulk is not None:
            # stop BEFORE joining the workers: any chunk still staged is
            # simply never committed — the durable cursor stays at the
            # last completed part, so the next engine over the same
            # store re-executes it (exactly-once by idempotent rewrite)
            self.bulk.stop()
        self.deploy.close()
        self.capacity.stop()  # no-op unless the timer thread was started
        deadline = time.monotonic() + timeout  # glomlint: disable=conc-raw-clock -- the drain deadline must track wall time: under a fake test clock the joins would otherwise never time out
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))  # glomlint: disable=conc-raw-clock -- paired with the wall-clock deadline above

        self._threads = []
        if self.sessions is not None and self._session_spill_dir:
            # spill AFTER the workers drained AND in-flight session
            # frames completed their put (admission is gated on _stop, so
            # the count only goes down): an acknowledged frame's state
            # must be in the spill — "nothing accepted is dropped" covers
            # sessions too.  A crash mid-spill leaves the previous spill
            # intact (atomic tmp+rename).
            with self._session_cv:
                drained = self._session_cv.wait_for(
                    lambda: self._session_inflight == 0,
                    timeout=max(0.0, deadline - time.monotonic()),  # glomlint: disable=conc-raw-clock -- paired with the wall-clock drain deadline above
                )
            if not drained:
                warnings.warn(
                    f"{self._session_inflight} session frame(s) still in "
                    f"flight at the drain deadline; spilling without them",
                    stacklevel=2)
            try:
                self.sessions.spill(self._session_spill_dir)
            except OSError as e:
                warnings.warn(f"session spill failed ({e}); fleet reboots "
                              f"cold", stacklevel=2)
        if self.tracer.exporter is not None:
            # deterministic trace-log lifecycle (a later emit reopens in
            # append mode, matching the MetricLogger contract)
            self.tracer.exporter.close()

    # -- hot reload --------------------------------------------------------
    def _reload_failure(self, what: str, e: Exception) -> None:
        self.registry.counter(
            "serving_reload_failures",
            help="failed hot-reload polls/loads (engine kept old params)",
        ).inc()
        warnings.warn(
            f"{what} failed ({type(e).__name__}: {e}); continuing to serve "
            # glomlint: disable=conc-unguarded-attr -- warning text only; a stale step number in a log line is harmless
            f"step {self.step}",
            stacklevel=3,
        )

    def _poll_latest(self):
        """One newest-valid-step poll, with the ``reload`` fault-injection
        site threaded in front (io_error raises the way a flaky NFS/GCS
        mount would; corrupt_manifest reads as "nothing finalized", the
        hardened ``latest_step`` behavior)."""
        kind = faultinject.fire("reload")
        if kind == "io_error":
            raise faultinject.FaultError("injected reload io_error")
        if kind == "corrupt_manifest":
            warnings.warn("injected corrupt reload manifest", stacklevel=2)
            return None
        # artifact-scan based and integrity-verified: a torn newest write
        # is quarantined HERE and an older valid step offered instead.
        # newer_than skips verification for the step already being served
        # and below — the every-poll case must never stream a multi-GB
        # artifact's CRC just to learn nothing new landed
        return integrity.latest_valid_step(
            self.checkpoint_dir, observer=self._integrity_obs,
            # glomlint: disable=conc-unguarded-attr -- poll heuristic only: a stale step means one extra CRC pass, and the swap re-validates under _reload_lock
            newer_than=self.step,
        )

    def check_reload(self) -> bool:
        """One watcher poll: load + swap when a newer VALID checkpoint
        landed.  Returns True on a successful swap.  Never raises — the
        poll runs under bounded retry-with-backoff (transient I/O errors
        are the normal weather of network filesystems), corrupt artifacts
        are quarantined with restore falling back to the newest step that
        verifies, and any terminal failure leaves the old params serving
        with ``serving_reload_failures`` bumped — the watcher thread (and
        ``/healthz``) must outlive every checkpoint-side failure."""
        newest = None
        for attempt in range(self._reload_retries):
            try:
                newest = self._poll_latest()
                # the POLL succeeded (even if a retry was needed): the
                # filesystem is answering, so the watcher cadence snaps
                # back to normal regardless of whether a swap follows
                self._reload_failstreak = 0
                break
            except Exception as e:
                self._reload_failure("reload poll", e)
                if attempt + 1 >= self._reload_retries:
                    self._reload_failstreak += 1
                    return False
                self._sleep(self._reload_retry_base_s * (2 ** attempt))
        # glomlint: disable=conc-unguarded-attr -- double-checked: the unlocked fast path skips the lock on no-op polls and is re-checked under _reload_lock below
        if newest is None or newest <= self.step:
            return False
        # serialize with the staged-reload API: a router-driven commit and
        # the standalone watcher must never interleave their load+swap
        with self._reload_lock:
            if newest <= self.step:
                return False
            reload_span = self.tracer.start_trace(
                SPAN_RELOAD, attrs={"from_step": int(self.step),
                                    "to_step": int(newest)},
            )
            try:
                new_params = self._restore_placed(newest)
            except ckpt_lib.CorruptCheckpointError as e:
                # the bytes went bad between the verified poll and the read:
                # quarantine so the next poll falls back to an older valid
                # step
                self.tracer.end(reload_span, attrs={"error": repr(e)})
                integrity.quarantine(self.checkpoint_dir, newest,
                                     observer=self._integrity_obs,
                                     reason=str(e))
                self._reload_failure(f"hot reload of step {newest}", e)
                self._reload_failstreak += 1
                return False
            except Exception as e:
                self.tracer.end(reload_span, attrs={"error": repr(e)})
                self._reload_failure(f"hot reload of step {newest}", e)
                self._reload_failstreak += 1
                return False
            with self._lock:
                # NOTE: no rollback point here — the standalone watcher
                # never rolls back, and pinning the displaced device tree
                # would hold two full param sets resident forever.  Only
                # the fleet-coordinated commit_staged() keeps _prev (and
                # the router finalizes it away once the rollout lands).
                self._params = new_params
                self.step = newest
            self.tracer.end(reload_span)
            self._note_swap(newest)
            return True

    def _restore_placed(self, step: int):
        """Restore ``step`` onto the serving layout: re-quantize exactly
        like startup (a reload must land in the dtype layout the AOT
        executables were compiled against), place via :meth:`_place`
        (sharded engines re-shard identically), and block before
        returning — a swap must never make the first request after it pay
        the H2D transfer."""
        _, trees = ckpt_lib.restore(
            self.checkpoint_dir, {"params": self._template}, step=step,
        )
        new_params = self._place(
            serving_quant.quantize_tree(trees["params"], self.quant)
        )
        jax.block_until_ready(jax.tree_util.tree_leaves(new_params)[0])
        return new_params

    def _note_swap(self, step: int) -> None:
        self.registry.counter(
            "serving_param_reloads", help="successful checkpoint hot reloads",
        ).inc()
        self.registry.gauge(
            "serving_checkpoint_step", help="step of the params being served",
        ).set(step)
        # every swap path re-anchors the registry's primary record, so
        # the residency view (and /healthz's models block) never drifts
        # from what actually serves
        self.models.sync_primary("default", step, self._params)

    # -- staged (two-phase) reload: the fleet coordination primitive -------
    def stage_reload(self, step: Optional[int] = None) -> Optional[int]:
        """Phase one of a coordinated rollout: load + place the new params
        OFF the request path, but don't serve them.  ``step=None`` polls
        for the newest valid step newer than the one serving; a pinned
        ``step`` stages exactly that checkpoint (the router pins every
        replica to the same step so a checkpoint landing mid-rollout can't
        split the fleet).  Returns the staged step, or None when there is
        nothing to stage — nothing newer, already serving the pinned
        step (the coordinator reads ``serving_step`` to tell "already
        there" from "couldn't"), or the load failed.  Old params keep
        serving either way: staging is side-effect-free on the serving
        path.  Every attempt SUPERSEDES prior staging — a leftover tree
        from an aborted earlier rollout must never be committable."""
        with self._reload_lock:
            self._staged = None
            target = step
            if target is None:
                try:
                    target = self._poll_latest()
                except Exception as e:
                    self._reload_failure("stage poll", e)
                    return None
            if target is None or (step is None and target <= self.step):
                return None
            if target == self.step:
                # pinned to what's already serving: nothing to stage and
                # nothing to commit — the coordinator treats this replica
                # as trivially current (staged_step None, serving_step ==
                # target), so no rollback call can ever land on it
                return None
            try:
                params = self._restore_placed(int(target))
            except ckpt_lib.CorruptCheckpointError as e:
                integrity.quarantine(self.checkpoint_dir, int(target),
                                     observer=self._integrity_obs,
                                     reason=str(e))
                self._reload_failure(f"stage of step {target}", e)
                return None
            except Exception as e:
                self._reload_failure(f"stage of step {target}", e)
                return None
            self._staged = (int(target), params)
            return int(target)

    def commit_staged(self) -> Optional[int]:
        """Phase two: atomically swap the staged params in (one reference
        assignment — in-flight batches finish on the old tree).  The
        displaced params are kept as the rollback point.  Returns the new
        step, or the CURRENT step when nothing is staged (a replica whose
        stage was a no-op commits trivially)."""
        with self._reload_lock:
            if self._staged is None:
                return int(self.step)
            new_step, params = self._staged
            self._staged = None
            span = self.tracer.start_trace(
                SPAN_RELOAD, attrs={"from_step": int(self.step),
                                    "to_step": int(new_step),
                                    "phase": "commit"},
            )
            with self._lock:
                self._prev = (self.step, self._params)
                self._params = params
                self.step = new_step
            self.tracer.end(span)
            self._note_swap(new_step)
            return int(new_step)

    def abort_staged(self) -> bool:
        """Drop staged params (phase-one failure elsewhere in the fleet).
        Returns True when something was staged."""
        with self._reload_lock:
            had = self._staged is not None
            self._staged = None
            return had

    def finalize_reload(self) -> bool:
        """Release the rollback point after the fleet-wide rollout landed
        everywhere — the displaced device tree is a full second param set,
        and holding it past the rollout window would permanently double
        the engine's memory.  Returns True when something was released;
        afterwards :meth:`rollback` has nothing to revert to (by design:
        the rollback window IS commit -> finalize)."""
        with self._reload_lock:
            had = self._prev is not None
            self._prev = None
            return had

    def rollback(self) -> Optional[int]:
        """Swap back to the params the last commit displaced — the fleet
        coordinator's recovery move when a sibling replica's commit
        failed mid-rollout.  One-shot (the rollback point is consumed);
        returns the step now serving, or None with nothing to roll to."""
        with self._reload_lock:
            if self._prev is None:
                return None
            old_step, old_params = self._prev
            self._prev = None
            span = self.tracer.start_trace(
                SPAN_RELOAD, attrs={"from_step": int(self.step),
                                    "to_step": int(old_step),
                                    "phase": "rollback"},
            )
            with self._lock:
                self._params = old_params
                self.step = old_step
            self.tracer.end(span)
            self.registry.counter(
                "serving_reload_rollbacks",
                help="param swaps reverted by a fleet-coordinated rollback",
            ).inc()
            self.registry.gauge(
                "serving_checkpoint_step",
                help="step of the params being served",
            ).set(old_step)
            self.models.sync_primary("default", old_step, old_params,
                                     source="rollback")
            return int(old_step)

    def promote_candidate(self, step: int) -> int:
        """The deploy controller's local promote: the RESIDENT candidate
        becomes primary through the same atomic reference swap as a
        staged commit (no restore — the tree is already placed), keeping
        the displaced params as the staged-API rollback point until
        :meth:`finalize_reload`."""
        version = self.models.get("default", int(step))
        if version is None:
            raise KeyError(f"no resident default@{step} to promote")
        with self._reload_lock:
            span = self.tracer.start_trace(
                SPAN_RELOAD, attrs={"from_step": int(self.step),
                                    "to_step": int(step),
                                    "phase": "promote"},
            )
            with self._lock:
                self._prev = (self.step, self._params)
                self._params = version.params
                self.step = int(step)
            self.tracer.end(span)
            self._note_swap(int(step))
        return int(step)

    def _watch_loop(self) -> None:
        # consecutive FULLY-failed polls stretch the wait (doubling, capped
        # at reload_backoff_max x poll): a dead filesystem is polled
        # gently, and one answered poll snaps the cadence back to normal
        # (check_reload owns the streak — a poll that needed a transient
        # retry but ultimately answered resets it)
        while not self._stop.wait(
            self._reload_poll_s
            * min(2 ** self._reload_failstreak, self._reload_backoff_max)
        ):
            self.check_reload()
            if self.sessions is not None:
                # abandoned streams age out on the watcher cadence rather
                # than waiting for byte pressure to reclaim their HBM
                self.sessions.sweep()

    # -- request path ------------------------------------------------------
    def submit(self, endpoint: str, imgs: np.ndarray, *, ctx=None,
               tenant: Optional[str] = None, model: Optional[str] = None,
               version: Optional[int] = None):
        """Enqueue a ``(k, c, H, W)`` batch for ``endpoint``; returns the
        Future resolving to the endpoint's output for those ``k`` images.
        Raises :class:`Overloaded` (shed) or :class:`Closed` (shutting
        down) — the server maps both to structured 503s.  ``ctx`` (the
        request's root span) threads the trace through the batcher and
        executor.

        ``tenant`` passes the request through its admission quota
        (:class:`~glom_tpu.serving.batcher.TenantAdmission`; a tenant
        past its token bucket sheds with
        :class:`~glom_tpu.serving.batcher.TenantQuotaExceeded` — only
        its own traffic).  ``model`` targets a non-default registry
        model; ``version`` pins the default model's deploy-candidate
        step (the server derives it from
        :meth:`DeployController.assign`).  Items tagged differently
        share a flush but execute as separate groups."""
        if self.tenants is not None:
            try:
                self.tenants.admit(tenant, int(imgs.shape[0]))
            except TenantQuotaExceeded:
                self._note_tenant_shed(tenant)
                raise
        mkey = None
        if model is not None:
            if self.models.get(model) is None:
                raise ValueError(f"unknown model {model!r}; resident: "
                                 f"{self.models.models()}")
            mkey = (model, None)
        elif version is not None:
            mkey = ("default", int(version))
        batcher = self.batchers[endpoint]
        try:
            future = batcher.submit(np.ascontiguousarray(imgs, dtype=np.float32),
                                    size=imgs.shape[0], ctx=ctx,
                                    tenant=tenant, mkey=mkey)
        except Overloaded:
            if self.tenants is not None:
                # the tokens bought nothing — a GLOBAL queue shed must
                # not also burn the tenant's own future budget
                self.tenants.refund(tenant, int(imgs.shape[0]))
            self.registry.counter(
                "serving_shed_total", help="requests shed at queue capacity",
            ).inc()
            self._observe_saturation(endpoint)
            raise
        self._observe_saturation(endpoint)
        return future

    def _note_tenant_shed(self, tenant: Optional[str]) -> None:
        """Quota-shed accounting shared by the batched and session
        admission paths."""
        self.registry.counter(
            "serving_shed_total", help="requests shed at queue capacity",
        ).inc()
        self.registry.counter(
            self.registry.labeled("serving_tenant_shed_", tenant),
            help="requests shed at a tenant's admission quota",
        ).inc()

    def _resolve_group(self, endpoint: str, mkey):
        """``(params, cache, retired)`` for one execute group.  ``mkey``
        None is the default primary (the overwhelmingly common group and
        the only one most deployments ever see); ``("default", step)`` is
        the deploy candidate — when it was retired between submit and
        execute, the group falls back to the primary (safe: same config,
        and exactly the documented post-rollback contract); ``(model,
        None)`` is an extra registry model's primary."""
        if mkey is None:
            return self.params, self.caches[endpoint], False
        model, step = mkey
        if model == "default" and step is not None:
            version = self.deploy.candidate(step)
            if version is None:
                return self.params, self.caches[endpoint], True
            return version.params, version.caches[endpoint], False
        version = self.models.get(model)
        if version is None:
            raise RuntimeError(f"model {model!r} was retired with items "
                               f"in flight")
        return version.params, version.caches[endpoint], False

    def process_once(self, endpoint: str, *, block: bool = False,
                     timeout: Optional[float] = None) -> int:
        """Pull one batch (if a flush rule fired) and run it; returns the
        number of images served.  The worker thread loops the blocking
        form; tests call the non-blocking form directly.

        Items tagged with different ``mkey``s (deploy-candidate canary
        traffic, extra registry models) share the flush but execute as
        separate groups — one params tree per dispatch, each padded to
        its own bucket against already-warm AOT executables, so the
        partition costs no compiles.  A group's failure fails only its
        own items' futures.  With an active shadow deploy, the primary
        group's images are mirrored (non-blocking, lossy) onto the
        shadow executor after the primary futures resolve."""
        batcher = self.batchers[endpoint]
        batch = batcher.next_batch(block=block, timeout=timeout)
        if not batch:
            return 0
        # span contexts this batch reports under: the batch-level span
        # (created at take, carries the links) first — it feeds the
        # duration histograms — then each member request's root span (the
        # same physical pad/execute mirrored into every trace that paid
        # for it)
        batch_span = batch[0].batch_span
        n_total = sum(item.size for item in batch)
        groups: Dict = {}
        for item in batch:
            groups.setdefault(item.mkey, []).append(item)
        # assembly (the host-side concat into per-group device batches)
        # is timed once for the whole flush and mirrored into every
        # member trace, exactly as before the partition existed
        t_asm0 = self.tracer.clock()
        group_imgs = {}
        for mkey, items in groups.items():
            arrays = [item.payload for item in items]
            group_imgs[mkey] = (arrays[0] if len(arrays) == 1
                                else np.concatenate(arrays))
        if batch_span is not None or any(it.ctx is not None for it in batch):
            t_asm1 = self.tracer.clock()
            all_ctxs = ([batch_span] if batch_span is not None else []) + [
                it.ctx for it in batch if it.ctx is not None]
            for i, ctx in enumerate(all_ctxs):
                self.tracer.record(
                    SPAN_BATCH_ASSEMBLY, ctx, t_asm0, t_asm1,
                    attrs={"items": len(batch), "images": n_total},
                    observe=i == 0,
                )
        served = 0
        primary_imgs = None
        primary_out = None
        primary_items = ()
        primary_params = None
        batch_error = None
        for mkey, items in groups.items():
            imgs = group_imgs[mkey]
            n = imgs.shape[0]
            member_ctxs = [it.ctx for it in items if it.ctx is not None]
            contexts = ([batch_span] if batch_span is not None
                        else []) + member_ctxs
            bulk_token = None
            try:
                params, cache, retired = self._resolve_group(endpoint, mkey)
                exec_imgs = imgs
                if mkey is None and self.bulk is not None:
                    # scavenge: the bucket pads to ``bucket`` rows anyway
                    # — fill the residual with bulk samples and run the
                    # SAME warmed executable.  Online rows stay first, so
                    # everything below (futures, shadow mirror, quality
                    # sampling, accounting) sees only ``out[:n]``.
                    bucket = cache.pick(n)
                    if bucket is not None and bucket > n:
                        bulk_token = self.bulk.fill(endpoint, bucket - n)
                        if bulk_token is not None:
                            exec_imgs = np.concatenate(
                                [imgs, bulk_token.imgs])
                t0 = self._clock()
                out_all = np.asarray(cache(params, exec_imgs,
                                           tracer=self.tracer,
                                           contexts=contexts))
                out = out_all[:n] if bulk_token is not None else out_all
                if mkey is not None and mkey[1] is not None and not retired:
                    # canary group: the injected-candidate fault seam
                    # (chaos's "latency-injected checkpoint" — a delay is
                    # client-visible latency, never an error)
                    kind = self.deploy.injected_fault()
                    if kind == "delay":
                        self._sleep(self.deploy.fault_delay_s)
                    elif kind == "error":
                        raise faultinject.FaultError(
                            "injected candidate error")
            except Exception as e:
                if bulk_token is not None:
                    # rewind the staged bulk chunk: nothing was
                    # committed, the slots simply re-execute later
                    self.bulk.abandon(bulk_token)
                for item in items:
                    if not item.future.done():
                        item.future.set_exception(e)
                batch_error = e
                continue
            batch_s = self._clock() - t0
            offset = 0
            for item in items:
                item.future.set_result(out[offset:offset + item.size])
                offset += item.size
            if mkey is None:
                primary_imgs = imgs
                primary_out = out
                primary_items = items
                primary_params = params
            self._account_batch(endpoint, cache, n, batch_s)
            if bulk_token is not None:
                # commit AFTER the online futures resolved: sink part
                # write + durable cursor advance (exactly-once order)
                k = bulk_token.hi - bulk_token.lo
                self.bulk.complete(bulk_token, out_all[n:n + k])
            if mkey is not None and mkey[0] != "default":
                self.registry.counter(
                    self.registry.labeled("serving_model_requests_",
                                          mkey[0]),
                    help="images served per non-default registry model",
                ).inc(n)
            elif mkey is not None and not retired:
                self.registry.counter(
                    "deploy_canary_requests",
                    help="live images executed against the deploy "
                         "candidate",
                ).inc(n)
            served += n
        if batch_span is not None:
            self.tracer.end(batch_span,
                            attrs=({} if batch_error is None
                                   else {"error": repr(batch_error)}))
        if primary_imgs is not None and self.deploy.phase == "shadow":
            # the primary's outputs ride along: the shadow lane compares
            # candidate-vs-primary on the SAME mirrored batch
            self.deploy.mirror(endpoint, primary_imgs, primary_out)
        if (primary_imgs is not None and self.quality_cache is not None
                and self.quality.should_sample()):
            self._observe_quality(endpoint, primary_imgs, primary_items,
                                  primary_params)
        return served

    def _worker_loop(self, endpoint: str) -> None:
        batcher = self.batchers[endpoint]
        while True:
            served = self.process_once(endpoint, block=True, timeout=0.25)
            if served == 0 and batcher.closed and batcher.depth == 0:
                return

    # -- stateful session serving (the /session/* request path) ------------
    @property
    def sessions_enabled(self) -> bool:
        return self.sessions is not None

    def _session_state_struct(self, bucket: int) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct((bucket,) + self._state_tail,
                                    self._state_dtype)

    def _valid_spilled_state(self, shape, dtype) -> bool:
        # the spill normalizes dtype to float32 for npz portability;
        # _place_state casts back to the serving state dtype — so only
        # the SHAPE gates restore: it must be a bucket this engine
        # actually compiled (a ladder change makes old state unservable)
        del dtype
        return (len(shape) == 4
                and shape[0] in self.caches["session_cold"].buckets
                and tuple(shape[1:]) == self._state_tail)

    def _place_state(self, host_levels):
        arr = jnp.asarray(host_levels, dtype=self._state_dtype)
        if self._state_sharding is not None:
            return jax.device_put(arr, self._state_sharding)
        return jax.device_put(arr)

    def session_embed(self, session_id: str, imgs: np.ndarray, *, ctx=None,
                      tenant: Optional[str] = None):
        """One frame of a stateful session: warm-start from the session's
        resident column state at ``warm_iters`` when it exists, full cold
        settle otherwise.  Returns ``(embeddings, info)`` where ``info``
        carries ``cold`` / ``frames`` / ``iters`` (the response contract).

        Runs synchronously on the caller's thread — a session's frames
        are inherently ordered (frame k+1 consumes frame k's state), so
        micro-batching across requests buys nothing within a session;
        across sessions the per-session locks let the device interleave
        frames freely.  Everything device-side is an AOT bucket
        executable; the state never leaves the device between frames."""
        return self._session_frame(session_id, imgs, ctx=ctx,
                                   tenant=tenant, parse=False)

    def session_parse(self, session_id: str, imgs: np.ndarray, *, ctx=None,
                      tenant: Optional[str] = None):
        """One PARSE frame of a stateful session (``/session/parse``):
        the same carried-equilibrium update as :meth:`session_embed` —
        one shared column state per session, so parse and embed frames
        interleave freely — but the output is the packed islanding row,
        and ``info`` additionally carries per-image island DELTAS
        (:func:`glom_tpu.hierarchy.parse.island_deltas`) against the
        previous PARSE frame's labels, computed under the same
        per-session frame-ordering lock.  A cold frame (or the first
        parse frame of an embed-only session) reports every island as
        ``appeared``."""
        return self._session_frame(session_id, imgs, ctx=ctx,
                                   tenant=tenant, parse=True)

    def _session_frame(self, session_id: str, imgs: np.ndarray, *, ctx,
                       tenant, parse: bool):
        if self.sessions is None:
            raise RuntimeError(
                "sessions disabled on this engine (construct with "
                "warm_iters= to enable /session/embed and /session/parse)")
        if not serving_sessions.valid_session_id(session_id):
            raise ValueError(
                f"invalid session id {session_id!r} (want "
                f"{serving_sessions.SESSION_ID_RE.pattern})")
        # the bulkhead covers sessions too: a tenant past its bucket
        # sheds ITS frames before they consume inline device time (the
        # quota is shared with the batched endpoints — one promise about
        # the tenant, not one per endpoint)
        if self.tenants is not None:
            try:
                self.tenants.admit(tenant, int(imgs.shape[0]))
            except TenantQuotaExceeded:
                self._note_tenant_shed(tenant)
                raise
        imgs = np.ascontiguousarray(imgs, dtype=np.float32)
        b = imgs.shape[0]
        # parse frames run the SAME executables as embed frames — one
        # (batch, stateful) matrix for both — and add the islanding
        # post-pass on the carried state afterwards
        cold_cache = self.caches["session_cold"]
        warm_cache = self.caches["session_warm"]
        bucket = cold_cache.pick(b)
        if bucket is None:
            raise ValueError(
                f"session frame batch {b} exceeds the largest bucket "
                f"{cold_cache.max_bucket}")
        contexts = [ctx] if ctx is not None else []
        restart = None
        # admission + drain accounting: a draining engine rejects new
        # frames (the server maps Closed to the structured 503), and the
        # spill waits for every admitted frame's put — check and count
        # under one condition so no frame slips between them
        with self._session_cv:
            if self._stop.is_set():
                raise Closed("engine draining; session frame rejected")
            self._session_inflight += 1
        try:
            with self.sessions.locked(session_id):
                entry = self.sessions.get(session_id)
                if entry is not None and entry.batch != b:
                    # documented cold-restart: the state's aval is pinned
                    # to the session's original batch size; a client
                    # changing its per-frame image count starts a fresh
                    # equilibrium
                    self.sessions.reset(session_id)
                    entry, restart = None, "batch_changed"
                # glomlint: disable=conc-unguarded-attr -- heuristic step comparison: a reload racing this read at worst defers the cold restart to the next frame; the retired() check itself is locked
                if (entry is not None and entry.step != self.step
                        and self.deploy.retired(entry.step)):
                    # the state was computed by a candidate a rollback/
                    # abort retired: warm-iterating a retired version's
                    # equilibrium on primary params would straddle
                    # versions mid-stream — cold-restart instead
                    self.sessions.reset(session_id)
                    entry, restart = None, "version_retired"
                params = self.params  # snapshot: this frame runs whole on it
                # glomlint: disable=conc-unguarded-attr -- provenance/version labels; the candidate() lookup below re-validates against the live deploy record
                serving_step, canary = self.step, False
                cand_step = self.deploy.candidate_step
                if cand_step is not None:
                    # version pinning: a session with RESIDENT state stays
                    # on the version that computed it (its equilibrium
                    # must not straddle versions mid-stream); only a cold
                    # frame follows the deterministic canary assignment
                    assigned = (cand_step
                                if entry is not None
                                and entry.step == cand_step
                                else (self.deploy.assign(session_id)
                                      if entry is None else None))
                    if assigned is not None:
                        cv = self.deploy.candidate(assigned)
                        if cv is not None:
                            params = cv.params
                            serving_step, canary = cv.step, True
                t0 = self._clock()
                if entry is None:
                    out, new_levels = cold_cache(
                        params, imgs, tracer=self.tracer, contexts=contexts)
                    cold, frames = True, 1
                else:
                    out, new_levels = warm_cache(
                        params, imgs, state=entry.levels,
                        tracer=self.tracer, contexts=contexts)
                    cold, frames = False, entry.frames + 1
                if parse:
                    # the pack replaces the embed output; new_levels is
                    # bucket-shaped (the next frame's executable input),
                    # so the post-pass hits its warmed aval and only the
                    # result slices back to the real batch
                    out = self.caches["parse"].apply_post(new_levels)[:b]
                elapsed = self._clock() - t0
                self.sessions.put(session_id, new_levels, batch=b,
                                  bucket=bucket, step=serving_step,
                                  frames=frames)
                deltas = None
                if parse:
                    # still under the session lock: the delta pairs THIS
                    # frame's labels with the previous parse frame's —
                    # an interleaved frame must never tear the pairing
                    out = np.asarray(out)
                    deltas = self._note_parse_labels(session_id, out, cold)
        finally:
            with self._session_cv:
                self._session_inflight -= 1
                self._session_cv.notify_all()
        out = np.asarray(out)
        self._account_session(cold, b, elapsed, restart)
        info = {"cold": cold, "frames": frames,
                "iters": (self._session_cold_iters if cold
                          else self._session_warm_iters),
                "step": int(serving_step)}
        if canary:
            # the server routes this outcome to the candidate evaluators
            info["canary_step"] = int(serving_step)
        if restart is not None:
            info["restart"] = restart
        if deltas is not None:
            info["deltas"] = deltas
        return out, info

    #: retained per-session parse labels (host-side int32 grids) — the
    #: delta baseline; bounded so abandoned sessions can never grow an
    #: unbounded host-side map beside the byte-bounded device store
    _PARSE_LABELS_MAX = 4096

    def _note_parse_labels(self, session_id: str, packed: np.ndarray,
                           cold: bool):
        """Label bookkeeping for one parse frame (caller holds the
        session lock): diff against the previous parse frame's labels,
        then retain this frame's as the next baseline.  A cold frame
        (fresh equilibrium) never diffs against pre-restart labels."""
        c = self.config
        side = c.image_size // c.patch_size
        n = side * side
        cur = np.rint(packed[:, :c.levels * n]).astype(np.int32)
        cur = cur.reshape(packed.shape[0], c.levels, side, side)
        prev = None if cold else self._parse_labels.get(session_id)
        if prev is not None and prev.shape != cur.shape:
            prev = None
        deltas = [hierarchy_parse.island_deltas(
            None if prev is None else prev[i], cur[i])
            for i in range(cur.shape[0])]
        self._parse_labels[session_id] = cur
        while len(self._parse_labels) > self._PARSE_LABELS_MAX:
            self._parse_labels.pop(next(iter(self._parse_labels)))
        return deltas

    # -- similarity queries (the /similar request path) --------------------
    @property
    def similar_enabled(self) -> bool:
        return self._index is not None

    def similar(self, imgs: np.ndarray, *, level: Optional[int] = None,
                k: int = 5, ctx=None, tenant: Optional[str] = None):
        """Level-aware nearest-neighbor query (``/similar``): embed the
        query image(s) through the warmed ``index`` cache — the SAME
        forward the bulk build ran, so query and index vectors live in
        one space — then scan this replica's index shards
        (:class:`glom_tpu.hierarchy.index.LevelIndex`).  Below the top
        level the query is the image's per-patch vectors ("search by
        part"); at the top it is the patch-mean whole.  Runs inline on
        the caller's thread like a session frame: the device half is one
        AOT bucket executable, the scan is host-side mmap work."""
        if self._index is None:
            raise RuntimeError(
                "similarity index disabled on this engine (construct "
                "with index_dir= to enable /similar)")
        if self.tenants is not None:
            try:
                self.tenants.admit(tenant, int(imgs.shape[0]))
            except TenantQuotaExceeded:
                self._note_tenant_shed(tenant)
                raise
        imgs = np.ascontiguousarray(imgs, dtype=np.float32)
        b = imgs.shape[0]
        c = self.config
        level = c.levels - 1 if level is None else int(level)
        if not 0 <= level < c.levels:
            raise ValueError(f"level {level} outside [0, {c.levels})")
        if k < 1:
            raise ValueError(f"need k >= 1, got {k}")
        cache = self.caches["index"]
        if cache.pick(b) is None:
            raise ValueError(
                f"query batch {b} exceeds the largest bucket "
                f"{cache.max_bucket}")
        contexts = [ctx] if ctx is not None else []
        t0 = self._clock()
        states = np.asarray(cache(self.params, imgs, tracer=self.tracer,
                                  contexts=contexts))    # (b, n, L, d)
        results = []
        for i in range(b):
            if level == c.levels - 1:
                q = states[i, :, level, :].mean(axis=0, keepdims=True)
            else:
                q = states[i, :, level, :]
            results.append(self._index.query(q, level, k=k))
        elapsed = self._clock() - t0
        self._account_similar(b, elapsed)
        return results, {"level": level, "k": int(k),
                         "index": self._index.stats()}

    def _account_similar(self, images: int, elapsed_s: float) -> None:
        reg = self.registry
        with self._lock:
            self.request_count += 1
        reg.counter("serving_requests_total",
                    help="images served across endpoints").inc(images)
        reg.counter("serving_similar_queries",
                    help="similarity queries answered").inc()
        reg.histogram(
            "serving_similar_seconds",
            help="embed + index-scan time per similarity query",
            unit="seconds",
        ).observe(elapsed_s)
        new_compiles = self.caches["index"].poll_compiles()
        if new_compiles:
            reg.counter(
                "serving_xla_compiles",
                help="request-path XLA compiles after warmup "
                     "(must stay 0)",
            ).inc(new_compiles)

    def session_reset(self, session_id: str) -> bool:
        """Drop a session's state (``/session/reset``); the next frame
        settles cold.  Returns whether state existed.  Taken under the
        session's frame-ordering lock: a reset racing an in-flight frame
        must order as reset-then-frame or frame-then-reset — never "the
        frame's put silently undoes the acknowledged reset"."""
        if self.sessions is None:
            raise RuntimeError("sessions disabled on this engine")
        with self.sessions.locked(session_id):
            self._parse_labels.pop(session_id, None)
            return self.sessions.reset(session_id)

    def _account_session(self, cold: bool, images: int, elapsed_s: float,
                         restart) -> None:
        reg = self.registry
        with self._lock:
            self.request_count += 1
        reg.counter("serving_requests_total",
                    help="images served across endpoints").inc(images)
        mode = "cold" if cold else "warm"
        reg.counter(
            f"serving_session_{mode}_frames",
            help=f"session frames served {mode} "
                 + ("(full settle)" if cold else "(warm-started)"),
        ).inc()
        reg.histogram(
            f"serving_session_frame_seconds_{mode}",
            help=f"device time per {mode} session frame", unit="seconds",
        ).observe(elapsed_s)
        if restart is not None:
            reg.counter(
                "serving_session_cold_restarts",
                help="sessions restarted cold after a per-frame batch-size "
                     "change (eviction/failover colds surface as "
                     "serving_session_misses)",
            ).inc()
        # "parse" covers /session/parse's post-pass (and, via the shared
        # counter, its inner index executables)
        for cache_name in ("session_cold", "session_warm", "parse"):
            cache = self.caches.get(cache_name)
            if cache is None:
                continue
            new_compiles = cache.poll_compiles()
            if new_compiles:
                reg.counter(
                    "serving_xla_compiles",
                    help="request-path XLA compiles after warmup "
                         "(must stay 0)",
                ).inc(new_compiles)
        # fleet replicas disable the reload watcher (the router owns
        # rollouts), so TTL reclamation rides the traffic itself:
        # interval-gated, O(entries) only when it actually fires
        self.sessions.sweep(min_interval=max(1.0, self.sessions.ttl_s / 10.0))

    # -- accounting / overload forensics -----------------------------------
    def _account_batch(self, endpoint, cache, n, batch_s) -> None:
        reg = self.registry
        with self._lock:
            self.request_count += n
        reg.counter("serving_requests_total",
                    help="images served across endpoints").inc(n)
        reg.histogram(f"serving_batch_seconds_{endpoint}",
                      help="device batch execution time",
                      unit="seconds").observe(batch_s)
        bucket = cache.pick(n) or n
        reg.histogram("serving_batch_occupancy",
                      help="real images / bucket size per executed batch"
                      ).observe(n / bucket)
        # per-bucket occupancy: the capacity plane's padding-waste-per-
        # bucket series (cardinality-bounded through labeled(), like the
        # per-bucket execute-span histograms)
        reg.histogram(reg.labeled("serving_batch_occupancy_b", bucket),
                      help="real images / bucket size for one bucket"
                      ).observe(n / bucket)
        reg.gauge("serving_queue_depth", help="queued images"
                  ).set(self.batchers[endpoint].depth)
        new_compiles = cache.poll_compiles()
        if new_compiles:
            reg.counter(
                "serving_xla_compiles",
                help="request-path XLA compiles after warmup (must stay 0)",
            ).inc(new_compiles)

    # -- model-quality telemetry (glom_tpu.obs.quality) --------------------
    def poll_quality_compiles(self) -> None:
        """Fold the quality post-pass's compile count into the shared
        ``serving_xla_compiles`` budget — the post-pass is AOT-warmed
        like every endpoint, so the zero-after-warmup invariant covers
        it (and a regression here fails the same acceptance)."""
        qc = self.quality_cache
        if qc is None:
            return
        new_compiles = qc.poll_compiles()
        if new_compiles:
            self.registry.counter(
                "serving_xla_compiles",
                help="request-path XLA compiles after warmup (must stay 0)",
            ).inc(new_compiles)

    def _observe_quality(self, endpoint: str, imgs, items, params) -> None:
        """One SAMPLED primary batch through the quality post-pass: the
        jitted fn returns PER-IMAGE signal rows (bucket padding was
        already sliced off by the cache), each request's rows are
        averaged back to per-request signals, and both the quality plane
        (sketches/drift/gauges) and the quality-kind SLOs observe them.
        Telemetry must never fail a served batch: post-pass errors count
        and return."""
        import hashlib

        try:
            mat = np.asarray(self.quality_cache(params, imgs))
        except Exception:  # glomlint: disable=conc-broad-except -- counted below; telemetry must never fail a served batch
            self.registry.counter(
                "quality_post_pass_failures",
                help="quality post-pass executions that raised "
                     "(telemetry-only; the served batch was unaffected)",
            ).inc()
            return
        self.poll_quality_compiles()
        levels = self.config.levels
        offset = 0
        for item in items:
            rows = mat[offset:offset + item.size]
            offset += item.size
            if rows.size == 0:
                continue
            signals = unpack_signals(rows.mean(axis=0), levels)
            trace_id = getattr(item.ctx, "trace_id", None)
            # the INPUT fingerprint: what a quality_drift bundle names so
            # an offending input is findable after the request is gone
            fingerprint = hashlib.sha1(
                np.ascontiguousarray(item.payload).tobytes()).hexdigest()[:16]
            flat = self.quality.observe(
                signals, trace_id=trace_id, tenant=item.tenant,
                version=self.step, fingerprint=fingerprint)  # glomlint: disable=conc-unguarded-attr -- version label only needs to be roughly current; a reload mid-pass mislabels one sample
            if self._slo is not None:
                with self._slo_lock:
                    self._slo.observe_quality(
                        flat, endpoint=endpoint, trace_id=trace_id,
                        step=self.request_count,  # glomlint: disable=conc-unguarded-attr -- debounce cursor only needs to be roughly current, same contract as observe_outcome
                        tenant=item.tenant, fingerprint=fingerprint)

    def _observe_saturation(self, endpoint: str) -> None:
        batcher = self.batchers[endpoint]
        # the whole observe-decide-capture path runs under the lock:
        # handler threads race through here, and both the monitor's streak
        # arithmetic and the trigger engine's budget check are
        # read-modify-write (two racing threads could overshoot the
        # capture budget).  Captures are rare and the bundle write is
        # small, so holding the lock across it is fine.
        with self._lock:
            shed_total = batcher.stats.shed
            shed_delta = shed_total - self._shed_seen[endpoint]
            self._shed_seen[endpoint] = shed_total
            count = self.request_count
            detail = self._saturation[endpoint].update(
                batcher.depth, batcher.max_queue, shed_delta,
            )
            if detail is not None:
                self.registry.counter(
                    "serving_queue_saturation_events",
                    help="sustained-overload detections",
                ).inc()
                detail["endpoint"] = endpoint
                if self._forensics is not None and self._triggers.fire(
                    TRIGGER_QUEUE_SATURATION, count
                ):
                    path = self._forensics.capture(
                        TRIGGER_QUEUE_SATURATION, count, detail, trace=False,
                    )
                    if path is None:
                        self._triggers.refund(TRIGGER_QUEUE_SATURATION, count)
        self.registry.gauge("serving_queue_depth", help="queued images"
                            ).set(batcher.depth)

    def observe_outcome(self, endpoint: str, latency_ms: Optional[float],
                        error: bool, trace_id: Optional[str] = None,
                        tenant: Optional[str] = None,
                        version: Optional[int] = None) -> None:
        """One request's terminal outcome, fed to the SLO burn-rate
        evaluators (the server calls this for successes AND errors —
        sheds burn the error budget too).  No-op without configured SLOs.
        Serialized under its OWN lock (the evaluators and the trigger
        engine's budget arithmetic are read-modify-write, and handler
        threads race through here), NOT the engine lock: a burn capture's
        bundle write must never stall the batch worker's accounting or
        the hot-reload param swap.  ``request_count`` is read unlocked —
        the debounce step only needs to be roughly current.

        ``tenant`` mints the per-tenant outcome metrics (cardinality-
        guarded) and scopes any per-tenant SLOs.  ``version`` routes a
        live CANARY outcome to the deploy candidate's evaluators INSTEAD
        of the primary SLOs — the candidate's sins (and virtues) are the
        deploy layer's evidence, never the primary's burn, mirroring the
        shadow contract."""
        if tenant is not None:
            self.registry.counter(
                self.registry.labeled("serving_tenant_requests_", tenant),
                help="requests answered per tenant (all outcomes)",
            ).inc()
            if error:
                self.registry.counter(
                    self.registry.labeled("serving_tenant_errors_", tenant),
                    help="5xx-class outcomes per tenant",
                ).inc()
            if latency_ms is not None:
                self.registry.histogram(
                    self.registry.labeled("serving_tenant_latency_ms_",
                                          tenant),
                    help="request latency per tenant", unit="ms",
                ).observe(latency_ms)
        if version is not None:
            if version == self.deploy.candidate_step:
                self.deploy.observe_candidate(endpoint, latency_ms, error,
                                              trace_id=trace_id,
                                              tenant=tenant)
            # else: the candidate was retired while this request was in
            # flight — the sample belongs to NEITHER side (the candidate's
            # evaluators are gone; the primary didn't necessarily serve
            # it), and feeding the retired candidate's degraded latencies
            # into the primary's burn evaluators would page on a healthy
            # primary during exactly the rollback it just executed
            return
        if self._slo is None:
            return
        with self._slo_lock:
            self._slo.observe(endpoint, latency_ms, error,
                              # glomlint: disable=conc-unguarded-attr -- debounce cursor only needs to be roughly current (documented above); _lock under _slo_lock would invert the batcher's order
                              trace_id=trace_id, step=self.request_count,
                              tenant=tenant)

    # -- debug plane (pulled by glom_tpu.obs.observatory) ------------------
    def debug_forensics(self) -> dict:
        """The ``/debug/forensics`` payload: this replica's bundle
        manifests, registry snapshot, and recent SLO firings — the
        evidence the fleet observatory correlates into ONE cross-replica
        incident bundle.  Read-only and cheap: a directory listing plus
        small JSON reads; never touches the request path."""
        import json as _json
        import os

        from glom_tpu.obs.forensics import MANIFEST, is_bundle_dir

        bundles = []
        root = self._forensics.root if self._forensics is not None else None
        if root and os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                path = os.path.join(root, name)
                if not is_bundle_dir(path):
                    continue
                try:
                    with open(os.path.join(path, MANIFEST)) as f:
                        manifest = _json.load(f)
                except (OSError, ValueError):
                    continue  # torn/mid-write manifest: next poll sees it
                bundles.append({"name": name, "manifest": manifest})
        # copy `fired` under the SLO lock: request threads append to the
        # deque inside _slo.observe(), and iterating a deque concurrent
        # with appends raises RuntimeError — precisely during the burn
        # incident this endpoint exists to document
        if self._slo is not None:
            with self._slo_lock:
                slo_fired = list(self._slo.fired)
        else:
            slo_fired = []
        return {
            "role": "engine",
            # glomlint: disable=conc-unguarded-attr -- point-in-time debug snapshot; the pull plane must never park behind a multi-second locked restore
            "step": int(self.step),
            "bundles": bundles,
            "registry": self.registry.snapshot(),
            "slo_fired": slo_fired,
        }

    # -- health ------------------------------------------------------------
    def health(self) -> dict:
        """The ``/healthz`` payload: liveness plus the config a client
        (loadgen) needs to build valid requests."""
        from glom_tpu.serving.sharded import mesh_axes_dict

        c = self.config
        # single reference read: a concurrent commit/abort may null
        # self._staged between a check and an index, and /healthz must
        # never crash during exactly the rollout windows it monitors
        staged = self._staged
        return {
            "status": "ok",
            # glomlint: disable=conc-unguarded-attr -- /healthz must answer DURING reloads; taking _reload_lock here would park liveness behind a multi-second restore (the staged read above has the same contract)
            "step": int(self.step),
            "warm": all(cache.warmed for cache in self.caches.values()),
            "queue_depth": {ep: b.depth for ep, b in self.batchers.items()},
            "buckets": list(self.caches["embed"].buckets),
            "quant": self.quant,
            "ff_impl": c.ff_impl,
            "donate_inputs": self.caches["embed"].donates_input,
            "mesh": mesh_axes_dict(self.mesh),
            "param_sharding": self.param_sharding,
            "sessions": (None if self.sessions is None else {
                "warm_iters": self._session_warm_iters,
                "cold_iters": self._session_cold_iters,
                **self.sessions.snapshot(),
            }),
            "staged_step": None if staged is None else int(staged[0]),
            # -- safe-deploy + multi-tenant surfacing ----------------------
            # the deploy phase rides /healthz so a router/operator can see
            # "this replica is canarying step N" without a dedicated poll
            "deploy": self.deploy.status(),
            "models": self.models.snapshot(),
            "tenants": (None if self.tenants is None
                        else self.tenants.snapshot()),
            # the capacity summary rides /healthz so the router's health
            # loop feeds its fleet series without a dedicated poll
            "capacity": self.capacity.summary(),
            # the quality summary rides along the same way — it carries
            # the serialized live sketches, so the router's health poll
            # IS the exact fleet-merge feed (merge is associative)
            "quality": self.quality.summary(),
            # the bulk summary rides /healthz too: per-shard durable
            # cursors are what the router's health loop remembers, so a
            # dead replica's unfinished range can be re-partitioned from
            # its last witnessed cursor
            "bulk": None if self.bulk is None else self.bulk.summary(),
            # the part-whole plane's contract surface: the islanding
            # thresholds clients parsed under, and this replica's index
            # shard inventory (what /similar fan-out actually scans)
            "hierarchy": {
                "parse_thresholds": list(self.parse_thresholds),
                "index": (None if self._index is None
                          else self._index.stats()),
            },
            "image_size": c.image_size,
            "patch_size": c.patch_size,
            "channels": c.channels,
            "levels": c.levels,
            "dim": c.dim,
        }
