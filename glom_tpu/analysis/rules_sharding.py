"""glomlint sharding-consistency rule pack — mesh axes, spec arity, and
flow-aware donation (the PR 6 SIGABRT family).

pjit-scale systems (arXiv:2204.06514) keep PartitionSpecs and the mesh
consistent by convention; when convention slips the failure is either a
hard trace-time error in a config nobody tested, or — donation — a
process abort.  These rules make the convention machine-checked:

  * ``shard-unknown-axis`` — whole-program: the axis vocabulary is
    DECLARED in ``parallel/mesh.py`` (tuple-of-string assignments to
    ``*AXES`` names, e.g. ``DEFAULT_AXES``/``MESH_AXES``); every string
    literal inside a ``P(...)``/``PartitionSpec(...)`` call, every
    string default of a ``*_axis``/``axis_name`` parameter, and every
    ``axis_name=`` string kwarg anywhere else must name a declared axis.
    A spec axis no config can produce fails the first time that config
    is actually run — this rule fails it at lint time.
  * ``shard-spec-arity`` — a ``shard_map(fn, ..., in_specs=(...))``
    whose in_specs tuple length differs from ``fn``'s positional arity
    (and, when both sides are literal tuples, out_specs length vs the
    returned tuple).  The mismatch is a trace-time TypeError that only
    fires for the sharded config path, i.e. never on the CPU tests.
  * ``shard-donation-flow`` — the CFG/dataflow upgrade of
    ``jax-donation-aliasing``: numpy/npz host-buffer taint is propagated
    over the control-flow graph (loop back edges, except-handler resume
    paths) to the donated argument of a donating jit.  The v1 rule's
    statement-ordered scan provably misses the retry shape — first
    attempt laundered, the except handler reassigns from the raw npz,
    the loop back edge feeds attempt two — which is exactly how the
    PR 6 crash family recurs.  Laundering (any non-numpy call boundary,
    e.g. the non-donating ``jax.jit(lambda t: t)`` identity or
    ``jax.device_put``) breaks the taint, same as v1.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.cfg import (
    CFGNode, build_cfg, header_exprs, solve_forward, _walk_no_scopes,
)
from glom_tpu.analysis.engine import (
    Finding, ModuleContext, Rule, dotted_name, terminal_name,
)
from glom_tpu.analysis.rules_jax import (
    DonationAliasingRule, _JIT_NAMES, _donated_indices,
)

_PSPEC_NAMES = {"P", "PartitionSpec"}
_AXES_DECL_RE = re.compile(r"AXES$")
_AXIS_PARAM_RE = re.compile(r"(_axis|axis_name)$")


def _str_elems(node: ast.AST) -> List[str]:
    """All string constants inside a (possibly nested-tuple) literal."""
    out: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return out


class ShardingAxisRule(Rule):
    name = "shard-unknown-axis"
    severity = "error"
    description = ("PartitionSpec / axis-param literal names a mesh axis "
                   "parallel/mesh.py never declares (*AXES tuples): no "
                   "buildable mesh can satisfy the spec — it fails at "
                   "trace time for exactly the config nobody tested")

    def __init__(self) -> None:
        self._declared: Set[str] = set()
        self._has_decl_file = False
        #: (path, line, axis, where, code)
        self._uses: List[Tuple[str, int, str, str, str]] = []

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.relpath.split("/")[-1] == "mesh.py":
            self._collect_declarations(ctx)
        self._collect_uses(ctx)
        return []

    def _collect_declarations(self, ctx: ModuleContext) -> None:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _AXES_DECL_RE.search(node.targets[0].id)):
                continue
            axes = _str_elems(node.value)
            if axes:
                self._has_decl_file = True
                self._declared.update(axes)
        # `DEFAULT_AXES + ("pipe",)` style: _str_elems over the BinOp value
        # already picked up the literal part; the Name part was collected
        # from its own assignment above.

    def _collect_uses(self, ctx: ModuleContext) -> None:
        rel = ctx.relpath
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                callee = terminal_name(node.func)
                if callee in _PSPEC_NAMES:
                    for arg in list(node.args) + [
                            kw.value for kw in node.keywords]:
                        for axis in _str_elems(arg):
                            self._uses.append(
                                (rel, node.lineno, axis,
                                 f"{callee}(...)",
                                 ctx.source_line(node.lineno)))
                else:
                    for kw in node.keywords:
                        if kw.arg == "axis_name" and isinstance(
                                kw.value, ast.Constant) and isinstance(
                                kw.value.value, str):
                            self._uses.append(
                                (rel, node.lineno, kw.value.value,
                                 "axis_name=",
                                 ctx.source_line(node.lineno)))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if ctx.relpath.split("/")[-1] == "mesh.py":
                    continue  # the declaration site itself
                args = node.args
                pos = args.posonlyargs + args.args
                defaults = args.defaults
                for arg, default in zip(pos[len(pos) - len(defaults):],
                                        defaults):
                    self._note_param_default(rel, arg, default, ctx)
                for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                    if default is not None:
                        self._note_param_default(rel, arg, default, ctx)

    def _note_param_default(self, rel: str, arg: ast.arg,
                            default: ast.AST, ctx: ModuleContext) -> None:
        if _AXIS_PARAM_RE.search(arg.arg) and isinstance(
                default, ast.Constant) and isinstance(default.value, str):
            self._uses.append(
                (rel, default.lineno, default.value,
                 f"default of {arg.arg!r}",
                 ctx.source_line(default.lineno)))

    def finalize(self) -> List[Finding]:
        if not self._has_decl_file:
            # no mesh.py in the analyzed set: nothing to be consistent
            # WITH (targeted single-file runs must not mass-flag)
            return []
        findings: List[Finding] = []
        for rel, line, axis, where, code in self._uses:
            if axis in self._declared:
                continue
            findings.append(Finding(
                rule=self.name, severity=self.severity, path=rel,
                line=line, col=0,
                message=f"axis {axis!r} in {where} is not declared in "
                        f"parallel/mesh.py ({sorted(self._declared)}): "
                        f"no mesh this project builds carries it — fix "
                        f"the name or declare the axis in MESH_AXES",
                code=code))
        return findings


class ShardMapArityRule(Rule):
    name = "shard-spec-arity"
    severity = "error"
    description = ("shard_map in_specs tuple length != the wrapped "
                   "function's positional arity (or literal out_specs vs "
                   "returned tuple): a trace-time TypeError only the "
                   "sharded config path ever hits")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        fns: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Lambda):
                fns[node.targets[0].id] = node.value
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "shard_map"
                    and node.args):
                continue
            target = node.args[0]
            fn: Optional[ast.AST] = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = fns.get(target.id)
            if fn is None:
                continue
            args = fn.args
            if args.vararg is not None:
                continue
            pos = args.posonlyargs + args.args
            arity = len(pos)
            if pos and pos[0].arg == "self":
                continue
            for kw in node.keywords:
                if kw.arg == "in_specs" and isinstance(kw.value, ast.Tuple):
                    n = len(kw.value.elts)
                    if n != arity:
                        findings.append(ctx.finding(
                            self, kw.value,
                            f"in_specs has {n} spec(s) but the wrapped "
                            f"function takes {arity} positional "
                            f"argument(s): shard_map will reject this at "
                            f"trace time — on the sharded config only"))
                elif kw.arg == "out_specs" and isinstance(kw.value,
                                                          ast.Tuple):
                    n_out = self._returned_tuple_len(fn)
                    if n_out is not None and n_out != len(kw.value.elts):
                        findings.append(ctx.finding(
                            self, kw.value,
                            f"out_specs has {len(kw.value.elts)} spec(s) "
                            f"but the wrapped function returns a "
                            f"{n_out}-tuple"))
        return findings

    @staticmethod
    def _returned_tuple_len(fn: ast.AST) -> Optional[int]:
        """Length of the returned tuple when EVERY return is a literal
        tuple of one consistent length; None otherwise (can't judge)."""
        if isinstance(fn, ast.Lambda):
            return (len(fn.body.elts)
                    if isinstance(fn.body, ast.Tuple) else None)
        lens: Set[int] = set()
        for node in _walk_no_scopes_body(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if not isinstance(node.value, ast.Tuple):
                    return None
                lens.add(len(node.value.elts))
        return lens.pop() if len(lens) == 1 else None


def _walk_no_scopes_body(fn):
    """Walk a function's body without descending into nested defs."""
    for stmt in fn.body:
        yield from _walk_no_scopes(stmt)


class DonationFlowRule(Rule):
    name = "shard-donation-flow"
    severity = "error"
    description = ("numpy/npz host-buffer taint reaches a donating jit "
                   "along a CFG path (loop back edge, except-handler "
                   "resume) — the flow-aware form of jax-donation-"
                   "aliasing (PR 6 SIGABRT family)")

    def __init__(self) -> None:
        # reuse v1's expression-taint semantics verbatim: a fact set of
        # tainted names + the same numpy-constructor source set, so the
        # two rules can never disagree about what taints an expression
        self._v1 = DonationAliasingRule()

    def check(self, ctx: ModuleContext) -> List[Finding]:
        donating: Dict[str, Set[int]] = {}
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func) in _JIT_NAMES):
                idxs = _donated_indices(node.value)
                tgt = terminal_name(node.targets[0])
                if idxs and tgt:
                    donating[tgt] = idxs
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if (isinstance(dec, ast.Call)
                            and dotted_name(dec.func) in _JIT_NAMES):
                        idxs = _donated_indices(dec)
                        if idxs:
                            donating[node.name] = idxs
        if not donating:
            return []
        findings: List[Finding] = []
        scopes: List[Tuple[str, list]] = [("<module>", ctx.tree.body)]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node.name, node.body))
        for scope_name, body in scopes:
            findings.extend(self._check_scope(scope_name, body, donating,
                                              ctx))
        return findings

    def _check_scope(self, scope_name: str, body: list,
                     donating: Dict[str, Set[int]], ctx: ModuleContext
                     ) -> List[Finding]:
        cfg = build_cfg(body)

        def transfer(node: CFGNode, state):
            stmt = node.stmt
            if stmt is None or node.kind == "handler":
                return state
            taint = set(state)
            if isinstance(stmt, ast.Assign):
                hot = self._v1._tainted(stmt.value, taint)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        (taint.add if hot else taint.discard)(tgt.id)
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        for el in tgt.elts:
                            if isinstance(el, ast.Name):
                                (taint.add if hot
                                 else taint.discard)(el.id)
            elif isinstance(stmt, ast.AnnAssign) and \
                    stmt.value is not None and \
                    isinstance(stmt.target, ast.Name):
                hot = self._v1._tainted(stmt.value, taint)
                (taint.add if hot else taint.discard)(stmt.target.id)
            elif isinstance(stmt, ast.AugAssign) and \
                    isinstance(stmt.target, ast.Name):
                if self._v1._tainted(stmt.value, taint):
                    taint.add(stmt.target.id)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self._v1._tainted(stmt.iter, taint):
                    for el in ast.walk(stmt.target):
                        if isinstance(el, ast.Name):
                            taint.add(el.id)
            return frozenset(taint)

        # a raising assignment assigned nothing: exception edges carry
        # the pre-statement taint
        results = solve_forward(cfg, transfer, may=True,
                                exc_transfer=lambda n, s: s)
        findings: List[Finding] = []
        seen: Set[Tuple[int, int]] = set()
        for node in cfg.stmt_nodes():
            if node not in results or node.kind == "handler":
                continue
            in_state = set(results[node][0])
            for expr in header_exprs(node.stmt):
                for call in _walk_no_scopes(expr):
                    if not isinstance(call, ast.Call):
                        continue
                    callee = terminal_name(call.func)
                    if callee not in donating:
                        continue
                    for i in donating[callee]:
                        if i < len(call.args) and self._v1._tainted(
                                call.args[i], in_state) and \
                                (call.lineno, i) not in seen:
                            seen.add((call.lineno, i))
                            findings.append(Finding(
                                rule=self.name, severity=self.severity,
                                path=ctx.relpath, line=call.lineno, col=0,
                                message=f"argument {i} of donating jit "
                                        f"{callee!r} in {scope_name!r} "
                                        f"derives from a numpy/npz host "
                                        f"buffer along a control-flow "
                                        f"path — donation frees memory "
                                        f"numpy owns; launder through a "
                                        f"non-donating jit identity on "
                                        f"EVERY path (including retry/"
                                        f"except resume paths)",
                                code=ctx.source_line(call.lineno)))
        return findings


SHARDING_RULES = (ShardingAxisRule, ShardMapArityRule, DonationFlowRule)
