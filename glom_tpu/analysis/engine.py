"""glomlint — project-native AST static analysis: the rule engine.

Seven PRs of review caught the same hazard classes by hand: donation
aliasing of numpy-backed trees (the PR 6 SIGABRT), check-then-act outside
the lock (the PR 7 commit-gate TOCTOU), raw ``time.time()`` in modules
that elsewhere take injectable clocks, request-path compiles.  This
module makes those reviews machine-checked:

  * :class:`Finding` — one diagnostic: rule id, severity, ``path:line``,
    message, and the stripped source line (``code``) the baseline keys on.
  * :class:`Rule` — per-file ``check(ctx)`` over a parsed
    :class:`ModuleContext`; whole-program rules (the lock-order graph)
    additionally implement ``finalize()`` after every file is dispatched.
  * Suppressions — ``# glomlint: disable=RULE[,RULE] -- reason`` on the
    finding's line (or a standalone comment on the line above).  A
    disable WITHOUT a reason does not suppress and is itself reported
    (``lint-bad-suppression``): the acceptance bar is that every
    suppression carries its justification.
  * Baseline — a committed JSON file of pre-existing findings keyed on
    ``(rule, path, stripped source line)`` (line-number free, so
    unrelated edits don't invalidate it).  Baselined findings never gate;
    anything beyond the baseline does.

The engine is stdlib-only (``ast``): it runs identically on a laptop, in
CI, and in the tier-1 suite with no accelerator and no jax import.  Rule
packs live in ``rules_jax`` / ``rules_concurrency`` / ``rules_obs`` /
``rules_paths`` / ``rules_sharding`` / ``rules_races`` (the last on the
:mod:`glom_tpu.analysis.callgraph` thread-root model);
``tools/lint.py`` is the CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

BASELINE_SCHEMA = 1

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``path`` is root-relative POSIX; ``code`` is the
    stripped source line (the baseline fingerprint component)."""

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    code: str = ""

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class ModuleContext:
    """One parsed file: source, line table, AST, suppression map."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        self.suppressions = _parse_suppressions(source)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule.name, severity=rule.severity,
                       path=self.relpath, line=line, col=col,
                       message=message, code=self.source_line(line))


class Rule:
    """Base rule: override :meth:`check`; whole-program rules accumulate
    state in ``check`` and emit from :meth:`finalize`."""

    name = "rule"
    severity = "warning"
    #: one line naming the historical bug this rule encodes (docs catalog)
    description = ""

    def check(self, ctx: ModuleContext) -> List[Finding]:
        return []

    def finalize(self) -> List[Finding]:
        return []


# -- suppressions ----------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*glomlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s+--\s*(\S.*)?)?\s*$"  # '-- <nothing>' parses as reasonless,
)                                # so it is reported, not silently ignored


def _parse_suppressions(source: str):
    """Map lineno -> (rules, reason, standalone).  ``standalone`` marks a
    comment-only line, which also covers the NEXT line (pylint style);
    an end-of-line disable covers only its own line.  Only actual COMMENT
    tokens count — a disable marker inside a string/docstring (e.g.
    documentation of the syntax) is never a suppression."""
    out: Dict[int, Tuple[Tuple[str, ...], Optional[str], bool]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out  # unparseable files surface as lint-parse-error anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rules = tuple(r.strip() for r in m.group(1).split(","))
        reason = m.group(2).strip() if m.group(2) else None
        standalone = tok.line.strip().startswith("#")
        out[lineno] = (rules, reason, standalone)
    return out


class _BadSuppressionRule(Rule):
    """Internal: a disable comment without a ``-- reason`` (it does not
    suppress; the reason IS the contract)."""

    name = "lint-bad-suppression"
    severity = "error"
    description = ("suppressions must carry a reason: "
                   "# glomlint: disable=RULE -- why this is safe")


_BAD_SUPPRESSION = _BadSuppressionRule()


def _matching_suppression(ctx: ModuleContext, f: Finding):
    """The suppression entry covering this finding's line, if any: a
    same-line disable, or a standalone disable on the line above."""
    ent_here = ctx.suppressions.get(f.line)
    if ent_here is not None and (f.rule in ent_here[0]
                                 or "all" in ent_here[0]):
        return ent_here
    ent_above = ctx.suppressions.get(f.line - 1)
    if (ent_above is not None and ent_above[2]
            and (f.rule in ent_above[0] or "all" in ent_above[0])):
        return ent_above
    return None


def apply_suppressions(ctx: ModuleContext,
                       findings: List[Finding]) -> Tuple[List[Finding],
                                                         List[Finding]]:
    """Split into (kept, suppressed); reasonless disables additionally
    yield a ``lint-bad-suppression`` finding per comment."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        entry = _matching_suppression(ctx, f)
        if entry is not None and entry[1]:
            suppressed.append(f)
        else:
            kept.append(f)
    # every reasonless disable is reported, matched or not: a comment that
    # LOOKS like a suppression but silently isn't one is worse than none
    for lineno, (_rules, reason, _standalone) in sorted(ctx.suppressions.items()):
        if reason is None:
            kept.append(Finding(
                rule=_BAD_SUPPRESSION.name, severity=_BAD_SUPPRESSION.severity,
                path=ctx.relpath, line=lineno, col=0,
                message="glomlint disable without '-- reason' (not honored): "
                        "every suppression must say why it is safe",
                code=ctx.source_line(lineno)))
    return kept, suppressed


# -- file discovery + dispatch ---------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_py_files(paths: Iterable[str]) -> List[str]:
    """Ordered, deduplicated by absolute path: overlapping arguments
    (``lint.py glom_tpu glom_tpu/serving``) must not analyze a file twice
    — duplicates would double-count against baseline budgets."""
    out: List[str] = []
    seen: set = set()

    def add(path: str) -> None:
        key = os.path.abspath(path)
        if key not in seen:
            seen.add(key)
            out.append(path)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in _SKIP_DIRS and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    add(os.path.join(dirpath, fn))
    return out


class _ParseErrorRule(Rule):
    name = "lint-parse-error"
    severity = "error"
    description = "file does not parse; nothing else can be checked"


_PARSE_ERROR = _ParseErrorRule()


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]          # post-suppression, pre-baseline
    suppressed: List[Finding]
    files: int = 0

    def by_rule(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))


def analyze(paths: Sequence[str], rules: Sequence[Rule],
            root: Optional[str] = None) -> AnalysisResult:
    """Dispatch every ``.py`` under ``paths`` through every rule, apply
    suppressions, then collect whole-program ``finalize()`` findings.
    Finalize findings that land on a concrete line of an analyzed file
    honor that line's inline suppressions too (the race pack's findings
    are per-access, so a reasoned disable must work there exactly like a
    per-file finding); reasonless disables were already reported in the
    per-file pass and are not re-reported here."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    ctxs: Dict[str, ModuleContext] = {}
    files = 0
    for path in iter_py_files(paths):
        files += 1
        abspath = os.path.abspath(path)
        rel = os.path.relpath(abspath, root)
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding(
                rule=_PARSE_ERROR.name, severity=_PARSE_ERROR.severity,
                path=rel.replace(os.sep, "/"), line=1, col=0,
                message=f"unreadable: {type(e).__name__}: {e}"))
            continue
        ctx = ModuleContext(abspath, rel, source)
        if ctx.parse_error is not None:
            findings.append(Finding(
                rule=_PARSE_ERROR.name, severity=_PARSE_ERROR.severity,
                path=ctx.relpath, line=ctx.parse_error.lineno or 1, col=0,
                message=f"syntax error: {ctx.parse_error.msg}",
                code=ctx.source_line(ctx.parse_error.lineno or 1)))
            continue
        ctxs[ctx.relpath] = ctx
        file_findings: List[Finding] = []
        for rule in rules:
            file_findings.extend(rule.check(ctx))
        kept, supp = apply_suppressions(ctx, file_findings)
        findings.extend(kept)
        suppressed.extend(supp)
    for rule in rules:
        for f in rule.finalize():
            ctx = ctxs.get(f.path)
            entry = (_matching_suppression(ctx, f)
                     if ctx is not None else None)
            if entry is not None and entry[1]:
                suppressed.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          files=files)


# -- baseline --------------------------------------------------------------

def _fingerprint(f: Finding) -> Tuple[str, str, str]:
    return (f.rule, f.path, f.code)


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline file -> fingerprint budget.  Missing file = empty."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    budget: Dict[Tuple[str, str, str], int] = {}
    for ent in data.get("findings", []):
        key = (ent["rule"], ent["path"], ent.get("code", ""))
        budget[key] = budget.get(key, 0) + int(ent.get("count", 1))
    return budget


def split_baseline(findings: Sequence[Finding],
                   budget: Dict[Tuple[str, str, str], int]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined): each baseline entry absorbs up to ``count``
    findings with the same (rule, path, source-line) fingerprint — the
    key survives pure line-number drift but not edits to the line."""
    remaining = dict(budget)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        key = _fingerprint(f)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[_fingerprint(f)] = counts.get(_fingerprint(f), 0) + 1
    entries = [{"rule": r, "path": p, "code": c, "count": n}
               for (r, p, c), n in sorted(counts.items())]
    payload = {"schema": BASELINE_SCHEMA, "findings": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)


# -- shared AST helpers (used by both rule packs) --------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last identifier of a Name/Attribute chain (``self.a.b`` -> ``b``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def is_lock_name(name: Optional[str]) -> bool:
    return name is not None and "lock" in name.lower()


def with_lock_attrs(node: ast.With) -> List[str]:
    """Lock attribute names acquired by ``with self.<lock>:`` items."""
    out = []
    for item in node.items:
        attr = is_self_attr(item.context_expr)
        if is_lock_name(attr):
            out.append(attr)
    return out


def child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """Nested statement blocks of a compound statement: body/orelse/
    finalbody, except-handler bodies, and match-case bodies.  The ONE
    block-iteration helper every rule walker shares, so structural
    recursion can't silently diverge between rules."""
    blocks: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        inner = getattr(stmt, field, None)
        if isinstance(inner, list) and inner and isinstance(inner[0],
                                                            ast.stmt):
            blocks.append(inner)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    for case in getattr(stmt, "cases", []) or []:
        blocks.append(case.body)
    return blocks


def is_compound(stmt: ast.stmt) -> bool:
    return bool(child_blocks(stmt))


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents
