"""glomlint interprocedural layer — class-aware call graph + thread roots.

The v1/v2 packs reason about one function (CFG dataflow) or one class
(the lock-order graph).  The race findings PRs 7-10 kept catching by
hand are *cross-thread* bugs: a read on the request path racing a write
on the watcher thread, a helper splitting a caller's critical section.
Seeing those requires knowing **which threads can execute which code** —
this module supplies that:

  * :class:`CallGraphBuilder` / :class:`CallGraph` — a whole-program,
    class-aware call graph over every analyzed module.  Scopes are
    methods, module functions, and the nested functions/lambdas defined
    inside them (a closure handed to ``Thread(target=...)`` is its own
    scope, with its calls resolved against the enclosing class).  Edges
    resolve ``self.m()`` within the class (including same-module base
    classes), bare names to nested functions then module functions —
    the resolution the lock-order rule already trusts, factored out and
    made program-wide.
  * **Thread-root discovery** — the places a new thread of control
    enters the code: ``Thread(target=...)`` / ``Timer(...)`` sites,
    ``executor.submit(fn)``, callback registrations (``callback=`` /
    ``on_*=`` keyword arguments taking a method reference), and the
    ``do_*``/``handle`` methods of ``*RequestHandler`` subclasses
    (every ``ThreadingHTTPServer`` request is its own thread).  Each
    public method additionally carries an *external* root: the caller's
    thread is a thread too — the race partner most analyses forget.
  * **Root propagation** — roots flow along call edges to a fixpoint,
    so every method is annotated with the set of thread roots that can
    reach it (:meth:`CallGraph.roots`).  A method reachable from two
    distinct roots can race with itself across threads; a root marked
    ``concurrent_with_self`` (thread started in a loop, executor pools,
    HTTP handlers) races with itself outright.

``__init__``/``__new__``/``__del__`` are excluded from root annotation:
constructor accesses happen before the object is published to any other
thread, and flagging them would bury the real findings.  (The known
blind spot — code *after* a ``start()`` inside ``__init__`` — is
accepted for the precision.)

Stdlib-``ast`` only, like the rest of the engine.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from glom_tpu.analysis.cfg import header_exprs as _stmt_exprs
from glom_tpu.analysis.engine import (
    ModuleContext, child_blocks as _child_blocks, dotted_name,
    is_self_attr, terminal_name,
)

__all__ = ["ThreadRoot", "Scope", "ClassInfo", "CallGraph",
           "CallGraphBuilder", "MODULE_SCOPE", "ROOT_EXCLUDED_METHODS"]

#: pseudo-class owner key suffix for module-level functions
MODULE_SCOPE = "<module>"

#: methods that run before/after the object is shared across threads
ROOT_EXCLUDED_METHODS = {"__init__", "__new__", "__del__"}

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}
_HANDLER_BASE_MARKER = "RequestHandler"
_HANDLER_METHODS_EXACT = {"handle", "handle_one_request"}
_CALLBACK_KWARGS = {"callback", "target", "function"}


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One source of a thread of control.  ``key`` is the identity the
    race rules count distinct roots by; ``concurrent_with_self`` marks
    roots that can run two instances at once (executor pools, HTTP
    handler threads, a Thread started inside a loop)."""

    kind: str                       # thread|timer|executor|callback|http-handler|external
    key: str
    path: str
    line: int
    concurrent_with_self: bool = False

    def describe(self) -> str:
        return f"{self.kind} @{self.path}:{self.line}"


@dataclasses.dataclass
class Scope:
    """One unit of executable code: a method, a module function, or a
    nested function/lambda inside one (``name`` is dotted for nested
    scopes: ``"shutdown.drain"``)."""

    owner: str                      # "relpath::Class" or "relpath::<module>"
    name: str
    node: ast.AST                   # FunctionDef / AsyncFunctionDef / Lambda
    relpath: str
    #: resolved same-class / same-module call targets, with the line
    calls: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    #: unresolved thread-root sites found lexically in this scope:
    #: (kind, ref, line, in_loop) where ref is ("self", name) |
    #: ("name", name) | ("lambda", Lambda node)
    root_sites: List[Tuple[str, tuple, int, bool]] = dataclasses.field(
        default_factory=list)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.owner, self.name)

    @property
    def method_name(self) -> str:
        """The directly-invocable method this scope belongs to (the head
        of the dotted name)."""
        return self.name.split(".", 1)[0]

    @property
    def is_public(self) -> bool:
        head = self.method_name
        if head in ROOT_EXCLUDED_METHODS:
            return False
        if "." in self.name:
            return False            # a closure is not an entry point
        return (not head.startswith("_")) or (
            head.startswith("__") and head.endswith("__"))


@dataclasses.dataclass
class ClassInfo:
    key: str                        # "relpath::Name"
    name: str
    relpath: str
    node: ast.ClassDef
    bases: Tuple[str, ...]
    scopes: Dict[str, Scope] = dataclasses.field(default_factory=dict)

    @property
    def is_request_handler(self) -> bool:
        return any(_HANDLER_BASE_MARKER in b for b in self.bases)


class _ScopeCollector:
    """Walk ONE scope's statements (never descending into nested
    function/class bodies — those are their own scopes) collecting call
    edges and thread-root sites."""

    def __init__(self, scope: Scope, in_class: bool):
        self.scope = scope
        self.in_class = in_class
        self._loop_depth = 0

    def run(self) -> List[Tuple[str, ast.AST]]:
        """Returns nested (name, FunctionDef|Lambda) scopes found."""
        self.nested: List[Tuple[str, ast.AST]] = []
        node = self.scope.node
        if isinstance(node, ast.Lambda):
            self._expr(node.body)
        else:
            self._block(node.body)
        return self.nested

    def _block(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.nested.append((stmt.name, stmt))
                continue
            if isinstance(stmt, ast.ClassDef):
                continue            # collected separately by the builder
            for expr in _stmt_exprs(stmt):
                self._expr(expr)
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # only the loop BODY repeats; the else block runs once
                self._loop_depth += 1
                self._block(stmt.body)
                self._loop_depth -= 1
                self._block(stmt.orelse)
                continue
            for inner in _child_blocks(stmt):
                self._block(inner)

    def _expr(self, node: ast.AST) -> None:
        for sub in _walk_exprs(node):
            if isinstance(sub, ast.Lambda):
                self.nested.append((f"<lambda@{sub.lineno}>", sub))
                continue
            if not isinstance(sub, ast.Call):
                continue
            self._call(sub)

    def _call(self, call: ast.Call) -> None:
        in_loop = self._loop_depth > 0
        callee_dotted = dotted_name(call.func)
        # call edges: self.m() in a class, bare f() anywhere
        if self.in_class:
            attr = is_self_attr(call.func)
            if attr:
                self.scope.calls.append((attr, call.lineno))
        if isinstance(call.func, ast.Name):
            self.scope.calls.append((call.func.id, call.lineno))
        # thread-root sites
        if callee_dotted in _THREAD_CTORS:
            ref = _callable_ref(_kwarg(call, "target"))
            if ref:
                self.scope.root_sites.append(("thread", ref, call.lineno,
                                              in_loop))
            return
        if callee_dotted in _TIMER_CTORS:
            fn = _kwarg(call, "function")
            if fn is None and len(call.args) >= 2:
                fn = call.args[1]
            ref = _callable_ref(fn)
            if ref:
                self.scope.root_sites.append(("timer", ref, call.lineno,
                                              in_loop))
            return
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "submit" and call.args):
            ref = _callable_ref(call.args[0])
            if ref:
                self.scope.root_sites.append(("executor", ref, call.lineno,
                                              True))
            return
        for kw in call.keywords:
            if kw.arg and (kw.arg in _CALLBACK_KWARGS
                           or kw.arg.startswith("on_")):
                ref = _callable_ref(kw.value)
                if ref:
                    self.scope.root_sites.append(
                        ("callback", ref, call.lineno, in_loop))


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _callable_ref(node: Optional[ast.AST]) -> Optional[tuple]:
    if node is None:
        return None
    attr = is_self_attr(node)
    if attr:
        return ("self", attr)
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if isinstance(node, ast.Lambda):
        return ("lambda", node)
    return None


def _walk_exprs(node: ast.AST):
    """ast.walk that stops at nested scope boundaries (their bodies are
    separate scopes) but yields the boundary node itself."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


class CallGraph:
    """The built graph: scopes, resolved edges, and per-scope thread-root
    annotations (:meth:`roots`)."""

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}
        self.scopes: Dict[Tuple[str, str], Scope] = {}
        self.edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        self.root_methods: Dict[Tuple[str, str], Set[ThreadRoot]] = {}
        self._roots_of: Dict[Tuple[str, str], frozenset] = {}

    def roots(self, key: Tuple[str, str]) -> frozenset:
        """Thread roots that can reach this scope (fixpoint-propagated).
        Empty frozenset for unknown/unreached scopes."""
        return self._roots_of.get(key, frozenset())

    def class_roots(self, cls_key: str) -> Set[ThreadRoot]:
        """Union of roots over all of a class's scopes."""
        out: Set[ThreadRoot] = set()
        for name in self.classes.get(cls_key, ClassInfo(
                cls_key, "", "", None, ())).scopes:
            out |= self.roots((cls_key, name))
        return out


class CallGraphBuilder:
    """Feed :meth:`add_module` every :class:`ModuleContext`, then
    :meth:`build` once — the whole-program pass."""

    def __init__(self) -> None:
        self.graph = CallGraph()
        #: relpath -> module owner key
        self._module_owner: Dict[str, str] = {}

    # -- collection --------------------------------------------------------

    def add_module(self, ctx: ModuleContext) -> None:
        if ctx.tree is None:
            return
        owner = f"{ctx.relpath}::{MODULE_SCOPE}"
        self._module_owner[ctx.relpath] = owner
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_scope(owner, node.name, node, ctx.relpath,
                                in_class=False)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._add_class(node, ctx.relpath)

    def _add_class(self, cls: ast.ClassDef, relpath: str) -> None:
        key = f"{relpath}::{cls.name}"
        bases = tuple(b for b in (terminal_name(base)
                                  for base in cls.bases) if b)
        info = ClassInfo(key=key, name=cls.name, relpath=relpath,
                         node=cls, bases=bases)
        self.graph.classes[key] = info
        for method in cls.body:
            if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_scope(key, method.name, method, relpath,
                                in_class=True, cls_info=info)

    def _add_scope(self, owner: str, name: str, node: ast.AST,
                   relpath: str, *, in_class: bool,
                   cls_info: Optional[ClassInfo] = None) -> None:
        scope = Scope(owner=owner, name=name, node=node, relpath=relpath)
        self.graph.scopes[scope.key] = scope
        if cls_info is not None:
            cls_info.scopes[name] = scope
        for sub_name, sub_node in _ScopeCollector(scope, in_class).run():
            self._add_scope(owner, f"{name}.{sub_name}", sub_node, relpath,
                            in_class=in_class, cls_info=cls_info)

    # -- resolution --------------------------------------------------------

    def _resolve(self, scope: Scope, ref_name: str
                 ) -> Optional[Tuple[str, str]]:
        """A called/targeted name, resolved: nested scope of this method
        first, then a sibling scope of the owner (method of the class /
        function of the module), then same-module base-class methods."""
        nested = (scope.owner, f"{scope.name}.{ref_name}")
        if nested in self.graph.scopes:
            return nested
        sibling = (scope.owner, ref_name)
        if sibling in self.graph.scopes:
            return sibling
        cls = self.graph.classes.get(scope.owner)
        if cls is not None:
            for base in cls.bases:
                base_key = (f"{cls.relpath}::{base}", ref_name)
                if base_key in self.graph.scopes:
                    return base_key
        mod_owner = self._module_owner.get(scope.relpath)
        if mod_owner is not None and mod_owner != scope.owner:
            mod_key = (mod_owner, ref_name)
            if mod_key in self.graph.scopes:
                return mod_key
        return None

    def build(self) -> CallGraph:
        g = self.graph
        # call edges + discovered roots
        for scope in g.scopes.values():
            targets = g.edges.setdefault(scope.key, set())
            for callee, _line in scope.calls:
                resolved = self._resolve(scope, callee)
                if resolved is not None and resolved != scope.key:
                    targets.add(resolved)
            for kind, ref, line, in_loop in scope.root_sites:
                if ref[0] == "lambda":
                    # the lambda was registered as a nested scope
                    resolved = self._resolve(scope,
                                             f"<lambda@{ref[1].lineno}>")
                else:
                    resolved = self._resolve(scope, ref[1])
                if resolved is None:
                    continue
                root = ThreadRoot(
                    kind=kind,
                    key=f"{kind}:{resolved[0]}.{resolved[1]}",
                    path=scope.relpath, line=line,
                    concurrent_with_self=in_loop or kind == "executor")
                g.root_methods.setdefault(resolved, set()).add(root)
        # HTTP request-handler methods: one (self-concurrent) root per
        # handler class — every request runs on its own server thread
        for cls in g.classes.values():
            if not cls.is_request_handler:
                continue
            for name, scope in cls.scopes.items():
                head = scope.method_name
                if head.startswith("do_") or head in _HANDLER_METHODS_EXACT:
                    root = ThreadRoot(
                        kind="http-handler", key=f"http-handler:{cls.key}",
                        path=cls.relpath, line=scope.node.lineno
                        if hasattr(scope.node, "lineno") else 1,
                        concurrent_with_self=True)
                    g.root_methods.setdefault(scope.key, set()).add(root)
        # the external root: public entry points run on the caller's
        # thread — the race partner of every background loop
        for scope in g.scopes.values():
            if scope.is_public:
                root = ThreadRoot(kind="external",
                                  key=f"external:{scope.owner}",
                                  path=scope.relpath,
                                  line=getattr(scope.node, "lineno", 1))
                g.root_methods.setdefault(scope.key, set()).add(root)
        # fixpoint: roots flow along call edges
        roots_of: Dict[Tuple[str, str], Set[ThreadRoot]] = {
            k: set(v) for k, v in g.root_methods.items()}
        work = list(roots_of)
        while work:
            key = work.pop()
            src = roots_of.get(key, set())
            for callee in g.edges.get(key, ()):
                dst = roots_of.setdefault(callee, set())
                if not src <= dst:
                    dst |= src
                    work.append(callee)
        g._roots_of = {k: frozenset(v) for k, v in roots_of.items()}
        return g
