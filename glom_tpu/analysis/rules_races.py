"""glomlint race rule pack — RacerD-style interprocedural race detection.

The single largest class of review-hardening findings across PRs 7-10
was cross-thread races neither the syntactic (v1) nor the
intraprocedural-CFG (v2) rules can see: the commit-gate TOCTOU, the
``SessionStore`` lock re-mint window, the healthz staged-step read, the
scrape-vs-request exemplar iteration, the spill-vs-inflight shutdown
race — all caught by humans, post hoc.  These rules sit on the
:mod:`glom_tpu.analysis.callgraph` thread-root model and the v2 CFG
solver:

  * ``conc-unguarded-attr`` — per-class *guarded-attribute inference*:
    for each ``self._attr``, infer its majority guard from the accesses
    the CFG solver proves occur under a held lock (``with self._lock:``
    blocks by containment, ``acquire()``/``release()`` pairs by
    must-analysis, helpers credited with the locks held at EVERY call
    site).  An access that escapes the inferred guard, in code reachable
    from two distinct thread roots (or one self-concurrent root), is a
    data race candidate — the PR 9 exemplar-iteration shape and the
    interprocedural form of the PR 7 commit-gate TOCTOU.
  * ``conc-lock-window`` — interprocedural lock-set summaries: a callee
    that releases a lock it did not itself acquire (the
    drop-and-reacquire helper) silently splits its caller's critical
    section in two; the call site under the lock is flagged (the PR 10
    ``SessionStore`` re-mint shape).  A ``release()`` inside the lock's
    own ``with`` block is flagged directly.
  * ``conc-escaping-state`` — escape analysis at the thread boundary: a
    mutable local (dict/list/set) captured by a ``Thread(target=...)``
    closure (or passed via ``args=``) and then used by the spawning
    function on a path with no ``join()`` between start and use is
    shared mutable state with no lock on either side — the PR 10
    spill-vs-inflight shutdown race.

Guard inference needs a *majority*: at least two proven-guarded accesses
covering at least half of all accesses.  Attributes holding sync
primitives (locks, conditions, events, queues, deques, thread handles)
are exempt — reading a lock attribute is how you use it.  Constructor
scopes (``__init__``/``__new__``/``__del__``) carry no thread roots:
pre-publication writes are not races.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from glom_tpu.analysis.callgraph import (
    CallGraphBuilder, ClassInfo, Scope, ThreadRoot,
)
from glom_tpu.analysis.cfg import (
    _walk_no_scopes, build_cfg, header_exprs as _stmt_exprs, solve_forward,
)
from glom_tpu.analysis.engine import (
    Finding, ModuleContext, Rule, child_blocks as _child_blocks,
    dotted_name, is_self_attr, parent_map,
)

#: attribute names recognized as guards when entered via ``with self.X:``
_GUARD_RE = re.compile(r"lock|mutex|cv|cond", re.IGNORECASE)

#: constructors whose values are sync/thread primitives — accesses to
#: these attributes are how threads coordinate, not what they guard
_SYNC_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "threading.Thread", "threading.Timer",
    "threading.local", "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Thread", "Timer",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
    "collections.deque", "deque",
}

#: method names that mutate their receiver (container mutation counts as
#: a write to the attribute holding the container)
_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "setdefault", "update", "sort",
    "reverse", "put", "put_nowait",
}

_MUTABLE_CTORS = {"dict", "list", "set", "bytearray", "defaultdict",
                  "OrderedDict", "Counter", "collections.defaultdict",
                  "collections.OrderedDict", "collections.Counter"}


def _is_guard_attr(name: Optional[str]) -> bool:
    return bool(name and _GUARD_RE.search(name))


# -- per-scope facts: accesses, held locks, release/call events ------------

@dataclasses.dataclass(frozen=True)
class Access:
    attr: str
    kind: str                     # "read" | "write"
    line: int
    locks: FrozenSet[str]         # guards held where the access executes


@dataclasses.dataclass(frozen=True)
class CallSite:
    callee: str                   # called name (self.m / bare f)
    line: int
    locks: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class ReleaseEvent:
    lock: str
    line: int
    locks: FrozenSet[str]         # held (with/must) where it executes
    with_held: bool               # True: releasing a with-held lock


@dataclasses.dataclass
class ScopeFacts:
    accesses: List[Access]
    calls: List[CallSite]
    releases: List[ReleaseEvent]


def _access_kind(node: ast.Attribute, parents: Dict) -> str:
    """Whether this ``self.X`` node is a write: a direct Store/Del, the
    receiver of a Store-context subscript/attribute (``self.x[k] = v``,
    ``self.x.y = v``), an AugAssign target, or the receiver of a
    mutating method call (``self.x.append(...)``)."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return "write"
    p = parents.get(node)
    if isinstance(p, ast.Subscript) and p.value is node and isinstance(
            p.ctx, (ast.Store, ast.Del)):
        return "write"
    if isinstance(p, ast.Attribute) and p.value is node:
        if isinstance(p.ctx, (ast.Store, ast.Del)):
            return "write"
        gp = parents.get(p)
        if isinstance(gp, ast.Call) and gp.func is p and \
                p.attr in _MUTATORS:
            return "write"
    return "read"


def _cfg_must_held(fn) -> Dict[int, FrozenSet[str]]:
    """id(stmt) -> guards PROVEN held (must-analysis over the CFG) via
    explicit ``self.X.acquire()``/``release()`` pairs — the v2 solver's
    acquire/release facts reused for guard inference.  A raising acquire
    never acquired (exc_transfer)."""
    def events(stmt):
        out = []
        for expr in _stmt_exprs(stmt):
            for node in _walk_no_scopes(expr):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                attr = is_self_attr(node.func.value)
                if not _is_guard_attr(attr):
                    continue
                if node.func.attr == "acquire" and not node.args \
                        and not node.keywords:
                    out.append(("acquire", attr))
                elif node.func.attr == "release":
                    out.append(("release", attr))
        return out

    # cheap pre-scan: most scopes lock via `with` only — don't pay for a
    # CFG + solve unless an explicit acquire/release call exists
    if not any(isinstance(n, ast.Attribute)
               and n.attr in ("acquire", "release")
               and _is_guard_attr(is_self_attr(n.value))
               for n in ast.walk(fn)):
        return {}
    try:
        cfg = build_cfg(fn)
    except RecursionError:          # pathological nesting: no credit
        return {}
    ev_by_node = {}
    any_events = False
    for node in cfg.stmt_nodes():
        if node.kind == "handler":
            continue
        ev = events(node.stmt)
        if ev:
            ev_by_node[node.index] = ev
            any_events = True
    if not any_events:
        return {}

    def transfer(node, state):
        for action, lock in ev_by_node.get(node.index, ()):
            state = state | {lock} if action == "acquire" else state - {lock}
        return state

    def exc_transfer(node, state):
        for action, lock in ev_by_node.get(node.index, ()):
            if action == "release":
                state = state - {lock}
        return state

    results = solve_forward(cfg, transfer, may=False,
                            exc_transfer=exc_transfer)
    held: Dict[int, FrozenSet[str]] = {}
    for node in cfg.stmt_nodes():
        if node in results:
            held[id(node.stmt)] = results[node][0]
    return held


def collect_scope_facts(scope: Scope) -> ScopeFacts:
    """Accesses / call sites / release events of one scope, each with the
    guards held where it executes: ``with self._lock:`` containment
    (exact) unioned with the CFG must-held acquire/release facts."""
    facts = ScopeFacts(accesses=[], calls=[], releases=[])
    node = scope.node
    parents = parent_map(node)
    if isinstance(node, ast.Lambda):
        _collect_exprs(node.body, frozenset(), facts, parents)
        return facts
    must_held = _cfg_must_held(node)

    def walk(body: Sequence[ast.stmt], with_held: FrozenSet[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            held = with_held | must_held.get(id(stmt), frozenset())
            for expr in _stmt_exprs(stmt):
                _collect_exprs(expr, held, facts, parents,
                               with_held=with_held)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                guards = frozenset(
                    a for a in (is_self_attr(item.context_expr)
                                for item in stmt.items)
                    if _is_guard_attr(a))
                walk(stmt.body, with_held | guards)
                continue
            for block in _child_blocks(stmt):
                walk(block, with_held)

    walk(node.body, frozenset())
    return facts


def _collect_exprs(expr: ast.AST, held: FrozenSet[str], facts: ScopeFacts,
                   parents: Dict, with_held: FrozenSet[str] = frozenset()
                   ) -> None:
    for node in _walk_no_scopes(expr):
        if isinstance(node, ast.Attribute):
            attr = is_self_attr(node)
            if attr is None:
                continue
            p = parents.get(node)
            if isinstance(p, ast.Call) and p.func is node:
                # a self-METHOD call, not state: record the call site
                facts.calls.append(CallSite(attr, node.lineno, held))
                continue
            if isinstance(p, ast.Attribute) and p.value is node and \
                    parents.get(p) is not None and \
                    isinstance(parents.get(p), ast.Call) and \
                    parents[p].func is p:
                # self.X.m(...): release/acquire bookkeeping + mutation
                if p.attr == "release" and _is_guard_attr(attr):
                    facts.releases.append(ReleaseEvent(
                        lock=attr, line=node.lineno, locks=held,
                        with_held=attr in with_held))
                    continue
                if p.attr == "acquire" and _is_guard_attr(attr):
                    continue        # the guard machinery itself
            if _is_guard_attr(attr):
                continue            # guards are used, not guarded
            facts.accesses.append(Access(
                attr=attr, kind=_access_kind(node, parents),
                line=node.lineno, locks=held))


def _sync_typed_attrs(cls: ClassInfo) -> Set[str]:
    """self-attributes assigned a sync/thread primitive anywhere in the
    class (typically ``__init__``)."""
    out: Set[str] = set()
    for node in ast.walk(cls.node):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in _SYNC_CTORS):
            continue
        for tgt in node.targets:
            attr = is_self_attr(tgt)
            if attr:
                out.add(attr)
    return out


def _resolve_in_class(cls: ClassInfo, caller: Scope, name: str
                      ) -> Optional[str]:
    nested = f"{caller.name}.{name}"
    if nested in cls.scopes:
        return nested
    if name in cls.scopes:
        return name
    return None


def _entry_credit(cls: ClassInfo, facts: Dict[str, ScopeFacts],
                  direct_roots: Set[str]) -> Dict[str, FrozenSet[str]]:
    """Locks a scope may be credited with at entry: the intersection,
    over every intra-class call site, of the locks held there (plus the
    caller's own credit).  Public methods and direct thread-root targets
    enter with nothing — the threading machinery calls them bare."""
    entry: Dict[str, FrozenSet[str]] = {}
    sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for sname, f in facts.items():
        caller = cls.scopes[sname]
        for call in f.calls:
            target = _resolve_in_class(cls, caller, call.callee)
            if target is not None and target != sname:
                sites.setdefault(target, []).append((sname, call.locks))

    def bare_entry(sname: str) -> bool:
        return (cls.scopes[sname].is_public or sname in direct_roots
                or sname not in sites)

    for sname in facts:
        if bare_entry(sname):
            entry[sname] = frozenset()
    for _ in range(len(facts) + 1):
        changed = False
        for sname in facts:
            if bare_entry(sname):
                continue
            acc: Optional[FrozenSet[str]] = None
            for caller, locks in sites[sname]:
                held = locks | entry.get(caller, frozenset())
                acc = held if acc is None else (acc & held)
            acc = acc or frozenset()
            if entry.get(sname) != acc:
                entry[sname] = acc
                changed = True
        if not changed:
            break
    return {s: entry.get(s, frozenset()) for s in facts}


# -- conc-unguarded-attr ---------------------------------------------------

class UnguardedAttrRule(Rule):
    name = "conc-unguarded-attr"
    severity = "error"
    description = ("shared attribute escapes its inferred majority lock "
                   "in code reachable from >=2 thread roots (PR 9 "
                   "exemplar-iteration / PR 7 commit-gate class): guard "
                   "the access or snapshot under the lock")

    #: inference needs a majority: >= MIN_GUARDED guarded accesses
    #: covering at least half of all accesses to the attribute
    MIN_GUARDED = 2

    def __init__(self) -> None:
        self._builder = CallGraphBuilder()
        self._ctx_lines: Dict[str, List[str]] = {}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        self._builder.add_module(ctx)
        self._ctx_lines[ctx.relpath] = ctx.lines
        return []

    def finalize(self) -> List[Finding]:
        graph = self._builder.build()
        findings: List[Finding] = []
        for cls_key in sorted(graph.classes):
            cls = graph.classes[cls_key]
            findings.extend(self._check_class(cls, graph))
        return findings

    def _check_class(self, cls: ClassInfo, graph) -> List[Finding]:
        roots_by_scope = {name: graph.roots((cls.key, name))
                          for name in cls.scopes}
        if not any(r.kind != "external"
                   for roots in roots_by_scope.values() for r in roots):
            return []               # no background thread ever runs here
        sync_attrs = _sync_typed_attrs(cls)
        facts = {name: collect_scope_facts(scope)
                 for name, scope in cls.scopes.items()}
        direct = {name for name in cls.scopes
                  if any(r.kind != "external" for r in
                         graph.root_methods.get((cls.key, name), ()))}
        entry = _entry_credit(cls, facts, direct)

        # group accesses per attribute, entry-credited, roots attached
        per_attr: Dict[str, List[Tuple[Access, str, frozenset]]] = {}
        for sname, f in facts.items():
            roots = roots_by_scope[sname]
            if not roots:
                continue            # unreachable / constructor scope
            for a in f.accesses:
                if a.attr in sync_attrs:
                    continue
                credited = dataclasses.replace(
                    a, locks=a.locks | entry[sname])
                per_attr.setdefault(a.attr, []).append(
                    (credited, sname, roots))

        findings: List[Finding] = []
        for attr in sorted(per_attr):
            findings.extend(self._check_attr(cls, attr, per_attr[attr]))
        return findings

    def _check_attr(self, cls: ClassInfo, attr: str,
                    accesses: List[Tuple[Access, str, frozenset]]
                    ) -> List[Finding]:
        if len(accesses) < 2:
            return []
        if not any(a.kind == "write" for a, _, _ in accesses):
            return []
        root_keys = {r.key for _, _, roots in accesses for r in roots}
        self_conc = any(r.concurrent_with_self
                        for _, _, roots in accesses for r in roots)
        if len(root_keys) < 2 and not self_conc:
            return []               # only one thread can ever touch it
        # majority-guard inference
        counts: Dict[str, int] = {}
        for a, _, _ in accesses:
            for lock in a.locks:
                counts[lock] = counts.get(lock, 0) + 1
        total = len(accesses)
        guard = None
        for lock in sorted(counts, key=lambda k: (-counts[k], k)):
            if counts[lock] >= self.MIN_GUARDED and \
                    2 * counts[lock] >= total:
                guard = lock
                break
        if guard is None:
            return []               # no inferable discipline to enforce
        findings: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for a, sname, roots in accesses:
            if guard in a.locks:
                continue
            partner = self._race_partner(a, roots, accesses)
            if partner is None:
                continue
            if (attr, a.line) in seen:
                continue
            seen.add((attr, a.line))
            p_access, p_roots = partner
            p_root = sorted(p_roots, key=lambda r: r.key)[0]
            line_text = ""
            lines = self._ctx_lines.get(cls.relpath)
            if lines and 1 <= a.line <= len(lines):
                line_text = lines[a.line - 1].strip()
            findings.append(Finding(
                rule=self.name, severity=self.severity, path=cls.relpath,
                line=a.line, col=0,
                message=f"{cls.name}.{attr} is guarded by self.{guard} on "
                        f"{counts[guard]}/{total} accesses but this "
                        f"{a.kind} in {sname!r} escapes it while a "
                        f"concurrent {p_access.kind} at line "
                        f"{p_access.line} can run on another thread "
                        f"({p_root.describe()}): hold self.{guard} here "
                        f"or snapshot the state under it",
                code=line_text))
        return findings

    @staticmethod
    def _race_partner(access: Access, roots: frozenset,
                      accesses: List[Tuple[Access, str, frozenset]]
                      ) -> Optional[Tuple[Access, frozenset]]:
        """An access that can run CONCURRENTLY with ``access`` such that
        at least one of the pair is a write and the two hold NO lock in
        common (a shared secondary lock — a poll lock serializing reader
        and writer — makes the pair mutually exclusive even when neither
        holds the majority guard).  Concurrency needs two distinct roots
        across the PAIR (identical root sets qualify when they contain
        two roots: the external caller and the watcher can each be
        mid-method at once) or one self-concurrent root."""
        my_keys = {r.key for r in roots}
        for other, _, o_roots in accesses:
            if other is access:
                continue
            if access.kind != "write" and other.kind != "write":
                continue
            if access.locks & other.locks:
                continue            # serialized by a common lock
            o_keys = {r.key for r in o_roots}
            if len(my_keys | o_keys) >= 2:
                return (other, o_roots)
            if any(r.concurrent_with_self for r in o_roots | roots):
                return (other, o_roots)
        return None


# -- conc-lock-window ------------------------------------------------------

class LockWindowRule(Rule):
    name = "conc-lock-window"
    severity = "error"
    description = ("a helper that releases a lock it did not acquire is "
                   "called with that lock held: the caller's critical "
                   "section silently splits in two (PR 10 SessionStore "
                   "lock re-mint window)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ".release(" not in ctx.source:
            return []               # no drop can exist without a release
        findings: List[Finding] = []
        builder = CallGraphBuilder()
        builder.add_module(ctx)
        graph = builder.build()
        for cls_key in sorted(graph.classes):
            findings.extend(self._check_class(graph.classes[cls_key], ctx))
        return findings

    def _check_class(self, cls: ClassInfo, ctx: ModuleContext
                     ) -> List[Finding]:
        facts = {name: collect_scope_facts(scope)
                 for name, scope in cls.scopes.items()}
        findings: List[Finding] = []
        # direct: releasing a with-held lock inside its own with block —
        # the window starts here AND __exit__ will double-release
        summary: Dict[str, Set[str]] = {}
        for sname, f in facts.items():
            uncredited: Set[str] = set()
            for rel in f.releases:
                if rel.with_held:
                    findings.append(ctx.finding(
                        self, _line_node(rel.line),
                        f"{cls.name}.{sname} releases self.{rel.lock} "
                        f"inside its own `with self.{rel.lock}:` block: "
                        f"the critical section is split open mid-body "
                        f"and the with-exit will release it again"))
                elif rel.lock not in rel.locks:
                    uncredited.add(rel.lock)
            summary[sname] = uncredited
        # transitive: a callee's uncredited releases propagate up until a
        # frame actually holds the lock — that call site is the window
        for _ in range(len(facts) + 1):
            changed = False
            for sname, f in facts.items():
                for call in f.calls:
                    target = _resolve_in_class(cls, cls.scopes[sname],
                                               call.callee)
                    if target is None or target == sname:
                        continue
                    inherit = summary.get(target, set()) - call.locks
                    if not inherit <= summary[sname]:
                        summary[sname] |= inherit
                        changed = True
            if not changed:
                break
        for sname, f in facts.items():
            for call in f.calls:
                target = _resolve_in_class(cls, cls.scopes[sname],
                                           call.callee)
                if target is None or target == sname:
                    continue
                windows = call.locks & summary.get(target, set())
                for lock in sorted(windows):
                    findings.append(ctx.finding(
                        self, _line_node(call.line),
                        f"{cls.name}.{sname} holds self.{lock} here but "
                        f"{call.callee!r} (or a helper it calls) releases "
                        f"and re-mints it: the critical section is TWO "
                        f"sections with a window between — another thread "
                        f"can run in the gap (PR 10 SessionStore re-mint "
                        f"shape); restructure so the helper runs outside "
                        f"the lock or never drops it"))
        return findings


def _line_node(line: int) -> ast.AST:
    node = ast.Pass()
    node.lineno = line
    node.col_offset = 0
    return node


# -- conc-escaping-state ---------------------------------------------------

class EscapingStateRule(Rule):
    name = "conc-escaping-state"
    severity = "error"
    description = ("a mutable local captured by a Thread target is used "
                   "by the spawner on a join-free path: shared mutable "
                   "state with no lock on either side (PR 10 "
                   "spill-vs-inflight shutdown race)")

    _THREAD_CTORS = {"threading.Thread", "Thread", "threading.Timer",
                     "Timer"}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not any(name in ctx.source for name in ("Thread(", "Timer(")):
            return []               # no thread boundary in this file
        findings: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(fn, ctx))
        return findings

    def _check_fn(self, fn, ctx: ModuleContext) -> List[Finding]:
        # cheap pre-scan: any Thread ctor at all?
        if not any(isinstance(n, ast.Call)
                   and dotted_name(n.func) in self._THREAD_CTORS
                   for n in _walk_no_scopes(fn)):
            return []
        nested = {n.name: n for n in fn.body if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for stmt in ast.walk(fn):
            body = getattr(stmt, "body", None)
            if not isinstance(body, list):
                continue
            for n in body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.setdefault(n.name, n)
        mutable_locals = self._mutable_locals(fn)
        if not mutable_locals:
            return []
        local_locks = self._local_locks(fn)
        sites = self._thread_sites(fn, nested, mutable_locals, local_locks)
        if not sites:
            return []
        stmt_guards = self._stmt_guards(fn, local_locks)
        findings: List[Finding] = []
        cfg = build_cfg(fn)
        for site_stmt, tvar, captured, target_writes, target_guards in sites:
            findings.extend(self._check_site(
                fn, cfg, site_stmt, tvar, captured, target_writes,
                target_guards, stmt_guards, ctx))
        return findings

    @staticmethod
    def _local_locks(fn) -> Set[str]:
        """Locals bound to sync primitives: a ``with <lock>:`` around
        both sides of a captured name's accesses is real discipline."""
        out: Set[str] = set()
        for node in _walk_no_scopes(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and dotted_name(
                    node.value.func) in _SYNC_CTORS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    @staticmethod
    def _stmt_guards(fn, local_locks: Set[str]
                     ) -> Dict[int, FrozenSet[str]]:
        """id(stmt) -> local locks lexically held (``with <lock>:``
        containment) when the statement's header evaluates."""
        guards: Dict[int, FrozenSet[str]] = {}

        def walk(body, held: FrozenSet[str]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                guards[id(stmt)] = held
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    locks = frozenset(
                        item.context_expr.id for item in stmt.items
                        if isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id in local_locks)
                    walk(stmt.body, held | locks)
                    continue
                for block in _child_blocks(stmt):
                    walk(block, held)

        walk(fn.body, frozenset())
        return guards

    def _mutable_locals(self, fn) -> Set[str]:
        out: Set[str] = set()
        for node in _walk_no_scopes(fn):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp)) or (
                isinstance(v, ast.Call)
                and dotted_name(v.func) in _MUTABLE_CTORS)
            if not mutable:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        return out

    def _thread_sites(self, fn, nested, mutable_locals, local_locks):
        """(site stmt, thread var or None, captured mutable locals,
        names the target body writes, per-name guard locks).  Only a
        statement whose OWN header contains the Thread constructor is a
        site — a compound statement enclosing one is not (its body
        statements are)."""
        sites = []
        for stmt in _walk_no_scopes(fn):
            if not isinstance(stmt, ast.stmt):
                continue
            call = None
            for expr in _stmt_exprs(stmt):
                for n in _walk_no_scopes(expr):
                    if isinstance(n, ast.Call) and \
                            dotted_name(n.func) in self._THREAD_CTORS:
                        call = n
                        break
                if call is not None:
                    break
            if call is None:
                continue
            tvar = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                tvar = stmt.targets[0].id
            target = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
            captured: Set[str] = set()
            target_body: Optional[ast.AST] = None
            if isinstance(target, ast.Name) and target.id in nested:
                target_body = nested[target.id]
                captured |= self._free_names(target_body) & mutable_locals
            elif isinstance(target, ast.Lambda):
                target_body = target
                captured |= self._free_names(target) & mutable_locals
            for kw in call.keywords:
                if kw.arg in ("args", "kwargs") and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Name) and \
                                el.id in mutable_locals:
                            captured.add(el.id)
            if not captured:
                continue
            writes = (self._written_names(target_body)
                      if target_body is not None else set())
            guards = (self._target_guards(target_body, captured,
                                          local_locks)
                      if target_body is not None else {})
            sites.append((stmt, tvar, captured, writes, guards))
        return sites

    @staticmethod
    def _target_guards(target, captured: Set[str], local_locks: Set[str]
                       ) -> Dict[str, FrozenSet[str]]:
        """Per captured name: the local locks held around EVERY access
        of it inside the thread target (empty set = at least one bare
        access, i.e. no discipline to credit)."""
        guards: Dict[str, Optional[FrozenSet[str]]] = {}
        if isinstance(target, ast.Lambda):
            for n in ast.walk(target.body):
                if isinstance(n, ast.Name) and n.id in captured:
                    guards[n.id] = frozenset()
            return {k: v for k, v in guards.items() if v}

        def walk(body, held: FrozenSet[str]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    walk(stmt.body, frozenset())  # runs who-knows-where
                    continue
                if isinstance(stmt, ast.ClassDef):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    locks = frozenset(
                        item.context_expr.id for item in stmt.items
                        if isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id in local_locks)
                    for item in stmt.items:
                        note_exprs(item.context_expr, held)
                    walk(stmt.body, held | locks)
                    continue
                for expr in _stmt_exprs(stmt):
                    note_exprs(expr, held)
                for block in _child_blocks(stmt):
                    walk(block, held)

        def note_exprs(expr, held: FrozenSet[str]) -> None:
            for n in _walk_no_scopes(expr):
                if isinstance(n, ast.Name) and n.id in captured:
                    cur = guards.get(n.id)
                    guards[n.id] = held if cur is None else (cur & held)

        walk(target.body, frozenset())
        return {k: v for k, v in guards.items() if v}

    @staticmethod
    def _free_names(target) -> Set[str]:
        body = target.body if isinstance(target, ast.Lambda) else target
        bound: Set[str] = set()
        if not isinstance(target, ast.Lambda):
            a = target.args
            bound = {x.arg for x in (a.posonlyargs + a.args
                                     + a.kwonlyargs)}
            for node in ast.walk(target):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Store):
                    bound.add(node.id)
        names: Set[str] = set()
        for node in ast.walk(body if isinstance(body, ast.AST)
                             else target):
            if isinstance(node, ast.Name) and node.id not in bound:
                names.add(node.id)
        return names

    @staticmethod
    def _written_names(target) -> Set[str]:
        out: Set[str] = set()
        scan = target.body if isinstance(target, ast.Lambda) else target
        nodes = ast.walk(scan) if isinstance(scan, ast.AST) else []
        pm = parent_map(scan) if isinstance(scan, ast.AST) else {}
        for node in nodes:
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    out.add(node.id)
                    continue
                p = pm.get(node)
                if isinstance(p, ast.Subscript) and p.value is node and \
                        isinstance(p.ctx, (ast.Store, ast.Del)):
                    out.add(node.id)
                elif isinstance(p, ast.Attribute) and p.value is node:
                    gp = pm.get(p)
                    if isinstance(gp, ast.Call) and gp.func is p and \
                            p.attr in _MUTATORS:
                        out.add(node.id)
        return out

    def _check_site(self, fn, cfg, site_stmt, tvar, captured,
                    target_writes, target_guards, stmt_guards,
                    ctx: ModuleContext) -> List[Finding]:
        fact = f"unjoined@{site_stmt.lineno}"
        site_ids = {id(site_stmt)}

        def is_join(stmt) -> bool:
            if tvar is None:
                return False
            for n in _walk_no_scopes(stmt):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute) and n.func.attr == "join" \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == tvar:
                    return True
            # `for w in workers: w.join()` joins the whole thread list
            if isinstance(stmt, (ast.For, ast.AsyncFor)) and isinstance(
                    stmt.iter, ast.Name) and stmt.iter.id == tvar and \
                    isinstance(stmt.target, ast.Name):
                w = stmt.target.id
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Call) and isinstance(
                            n.func, ast.Attribute) and \
                            n.func.attr == "join" and isinstance(
                            n.func.value, ast.Name) and \
                            n.func.value.id == w:
                        return True
            return False

        def transfer(node, state):
            stmt = node.stmt
            if stmt is None:
                return state
            if id(stmt) in site_ids:
                return state | {fact}
            if is_join(stmt):
                return state - {fact}
            return state

        results = solve_forward(cfg, transfer, may=True)
        findings: List[Finding] = []
        reported: Set[str] = set()
        for node in cfg.stmt_nodes():
            stmt = node.stmt
            if node not in results or id(stmt) in site_ids or \
                    node.kind == "handler":
                continue
            if fact not in results[node][0]:
                continue
            if is_join(stmt):
                continue
            pm = parent_map(stmt)
            held_here = stmt_guards.get(id(stmt), frozenset())
            for expr in _stmt_exprs(stmt):
                for n in _walk_no_scopes(expr):
                    if not (isinstance(n, ast.Name) and n.id in captured):
                        continue
                    if n.id in reported:
                        continue
                    use_writes = isinstance(n.ctx, (ast.Store, ast.Del))
                    p = pm.get(n)
                    if isinstance(p, ast.Subscript) and p.value is n and \
                            isinstance(p.ctx, (ast.Store, ast.Del)):
                        use_writes = True
                    if isinstance(p, ast.Attribute) and p.value is n:
                        gp = pm.get(p)
                        if isinstance(gp, ast.Call) and gp.func is p and \
                                p.attr in _MUTATORS:
                            use_writes = True  # pending.clear() and kin
                    if not (use_writes or n.id in target_writes):
                        continue    # read-on-both-sides: no conflict
                    if held_here & target_guards.get(n.id, frozenset()):
                        continue    # both sides share a real lock
                    reported.add(n.id)
                    findings.append(ctx.finding(
                        self, n,
                        f"mutable local {n.id!r} is captured by the "
                        f"thread started at line {site_stmt.lineno} and "
                        f"used here on a path with no join() in between: "
                        f"the thread can still be "
                        f"{'writing' if n.id in target_writes else 'reading'}"
                        f" it (PR 10 spill-vs-inflight class) — join the "
                        f"thread first, or hand it a snapshot instead of "
                        f"the live object"))
        return findings


RACE_RULES = (UnguardedAttrRule, LockWindowRule, EscapingStateRule)
