"""glomlint path-sensitive rule pack — the review-finding classes that
per-file, flow-insensitive rules provably cannot catch.

Four PRs of review findings were *path* bugs: the resource was released,
just not on the path that mattered — a commit gate reopened on success
but not when a replica's commit raised (PR 7), a staged param tree
committed on the happy path and stranded after a failed prepare (PR 7),
a session spill that forgot to wait the in-flight drain barrier on one
shutdown route (PR 10).  These rules run the :mod:`cfg` dataflow engine
over every function and check *paths*, exception edges included:

  * ``res-leak-on-raise`` — a resource is acquired and released in the
    same function, but SOME path to an exit (normal or exceptional)
    misses the release and no ``finally`` guarantees it.  Recognized
    resource shapes: ``X.acquire()``/``X.release()`` pairs, gate events
    (``X.clear()``/``X.set()`` where X names a gate: *open/gate/admit/
    ready/dispatch/accept*), in-flight counters (``X.inflight += 1`` /
    ``-= 1`` style), and ``f = open(...)``/``f.close()``.  The
    inconsistency filter keeps it honest: a function that NEVER releases
    (a close-only helper — the reopen lives elsewhere by design) is not
    flagged; releasing on some paths but not others is the bug.
  * ``proto-paired-call`` — declarative protocol specs
    (:data:`PROTOCOL_SPECS`): a *begin* call must reach one of its
    *settle* calls on every path to an exit (``kind="settle"``), or a
    guarded action must be preceded by its barrier on every incoming
    path (``kind="precede"``).  Spec entries are ``"name"`` or
    ``"name:literal"`` — the latter additionally requires a string
    literal argument, so ``_admin(replica, "prepare")`` and
    ``_admin(replica, "commit")`` are different protocol events of the
    same callee.  Future subsystems register their pairing contracts by
    adding a spec row, not a rule class.
  * ``res-double-release`` — a release that is already-released on ALL
    incoming paths (must-analysis, so an `if`-guarded re-close or a
    release inside a loop body does not fire it).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from glom_tpu.analysis.cfg import (
    CFG, CFGNode, build_cfg, header_exprs, solve_forward, witness_path,
    _walk_no_scopes,
)
from glom_tpu.analysis.engine import (
    Finding, ModuleContext, Rule, dotted_name, terminal_name,
)

_GATE_RE = re.compile(r"open|gate|admit|accept|ready|dispatch",
                      re.IGNORECASE)
_COUNTER_RE = re.compile(r"inflight|in_flight|pending|outstanding",
                         re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class _Event:
    action: str          # "acquire" | "release"
    rid: str             # resource identity (dotted receiver / bound name)
    kind: str            # "pair" | "gate" | "counter" | "file"
    lineno: int


_RELEASE_VERBS = {
    "pair": "released (.release())",
    "gate": "reopened (.set())",
    "counter": "decremented",
    "file": "closed (.close())",
}
_ACQUIRE_VERBS = {
    "pair": "acquired",
    "gate": "closed (.clear())",
    "counter": "incremented",
    "file": "opened",
}


def _receiver_id(node: ast.AST) -> Optional[str]:
    """Stable resource identity for the receiver of a method call."""
    return dotted_name(node)


def _stmt_events(stmt: ast.stmt) -> List[_Event]:
    """Resource events this CFG node performs (header expressions only —
    body statements of compounds are their own nodes)."""
    events: List[_Event] = []
    # counter inc/dec: `X.inflight += 1` / `-= 1`
    if isinstance(stmt, ast.AugAssign):
        tgt = terminal_name(stmt.target)
        if tgt and _COUNTER_RE.search(tgt) and isinstance(
                stmt.op, (ast.Add, ast.Sub)):
            rid = dotted_name(stmt.target) or tgt
            action = "acquire" if isinstance(stmt.op, ast.Add) else "release"
            events.append(_Event(action, rid, "counter", stmt.lineno))
    # `f = open(...)` binds a closable resource
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
            isinstance(stmt.targets[0], ast.Name) and \
            isinstance(stmt.value, ast.Call) and \
            dotted_name(stmt.value.func) in ("open", "io.open"):
        events.append(_Event("acquire", stmt.targets[0].id, "file",
                             stmt.lineno))
    for expr in header_exprs(stmt):
        for node in _walk_no_scopes(expr):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            rid = _receiver_id(node.func.value)
            if rid is None:
                continue
            recv_tail = rid.rsplit(".", 1)[-1]
            if attr == "acquire" and not node.args and not node.keywords:
                # acquire WITH arguments (blocking=False / timeout=) is
                # conditional — whether the lock is held depends on the
                # return value, which a gen/kill fact cannot track
                events.append(_Event("acquire", rid, "pair", node.lineno))
            elif attr == "release":
                events.append(_Event("release", rid, "pair", node.lineno))
            elif attr == "clear" and _GATE_RE.search(recv_tail):
                events.append(_Event("acquire", rid, "gate", node.lineno))
            elif attr == "set" and _GATE_RE.search(recv_tail):
                events.append(_Event("release", rid, "gate", node.lineno))
            elif attr == "close":
                events.append(_Event("release", rid, "file", node.lineno))
    return events


def _cfg_events(cfg: CFG) -> Dict[int, List[_Event]]:
    out: Dict[int, List[_Event]] = {}
    for node in cfg.stmt_nodes():
        if node.kind == "handler":
            continue
        ev = _stmt_events(node.stmt)
        if ev:
            out[node.index] = ev
    return out


def _escapes(fn: ast.AST, rid: str) -> bool:
    """For a plain-name resource: ownership transfer out of the function
    (returned, yielded, stored onto an object, or passed to another
    call) — the caller releases, not this function."""
    if "." in rid:
        return False
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) and \
                node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == rid:
                    return True
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id == rid:
                            return True
        elif isinstance(node, ast.Call):
            # passed as an argument to anything but its own method call
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == rid:
                    return True
    return False


def _iter_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _quick_events(fn) -> List[_Event]:
    """Flat event scan over every statement of ``fn`` (nested defs
    included — over-approximate, used only to decide whether building a
    CFG can possibly pay off)."""
    out: List[_Event] = []
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.stmt):
            out.extend(_stmt_events(stmt))
    return out


class ResourceLeakRule(Rule):
    name = "res-leak-on-raise"
    severity = "error"
    description = ("resource released on some paths but not all — a gate "
                   "left closed / counter left high / handle left open on "
                   "an exception or early-return path (PR 7 commit-gate "
                   "class); release on every path or use try/finally")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in _iter_functions(ctx.tree):
            findings.extend(self._check_fn(fn, ctx))
        return findings

    def _check_fn(self, fn, ctx: ModuleContext) -> List[Finding]:
        quick = _quick_events(fn)
        # a CFG can only pay off when some resource is both acquired and
        # released in this function (the inconsistency filter, applied
        # before the expensive part)
        if not ({e.rid for e in quick if e.action == "acquire"}
                & {e.rid for e in quick if e.action == "release"}):
            return []
        cfg = build_cfg(fn)
        events = _cfg_events(cfg)
        if not events:
            return []
        acquired: Dict[str, Tuple[str, int, CFGNode]] = {}
        released: Set[str] = set()
        for idx, evs in events.items():
            for e in evs:
                if e.action == "acquire" and e.rid not in acquired:
                    acquired[e.rid] = (e.kind, e.lineno, cfg.nodes[idx])
                elif e.action == "release":
                    released.add(e.rid)
        # the inconsistency filter: a function that never releases is a
        # deliberate one-way helper, not a path bug
        rids = [r for r in acquired if r in released]
        if not rids:
            return []
        rids = [r for r in rids if not _escapes(fn, r)]
        if not rids:
            return []

        def transfer(node: CFGNode, state):
            for e in events.get(node.index, ()):  # noqa: B023
                if e.rid in rids:
                    if e.action == "acquire":
                        state = state | {e.rid}
                    else:
                        state = state - {e.rid}
            return state

        def exc_transfer(node: CFGNode, state):
            # the node's own exception edge: a raising acquire never
            # acquired; a release still counts (flagging the release's
            # own hypothetical failure would damn every finally block)
            for e in events.get(node.index, ()):
                if e.rid in rids and e.action == "release":
                    state = state - {e.rid}
            return state

        results = solve_forward(cfg, transfer, may=True,
                                exc_transfer=exc_transfer)
        findings: List[Finding] = []
        for rid in rids:
            kind, line, acq_node = acquired[rid]
            leaks: List[str] = []
            for exit_node, what in ((cfg.raise_exit, "an exception path"),
                                    (cfg.exit, "a return path")):
                if exit_node not in results:
                    continue
                if rid not in results[exit_node][0]:
                    continue
                path = witness_path(cfg, results, rid, acq_node, exit_node)
                via = ""
                concrete = [n for n in path[1:-1] if n.lineno is not None]
                if concrete:
                    via = f" (escapes via line {concrete[-1].lineno})"
                leaks.append(what + via)
            if leaks:
                findings.append(Finding(
                    rule=self.name, severity=self.severity,
                    path=ctx.relpath, line=line, col=0,
                    message=f"{kind} {rid!r} {_ACQUIRE_VERBS[kind]} in "
                            f"{fn.name!r} is not {_RELEASE_VERBS[kind]} on "
                            f"{' nor '.join(leaks)}: other paths release "
                            f"it, so this path is a leak — release on "
                            f"every path or wrap in try/finally",
                    code=ctx.source_line(line)))
        return findings


class DoubleReleaseRule(Rule):
    name = "res-double-release"
    severity = "warning"
    description = ("release of a resource that every incoming path has "
                   "already released (no re-acquire in between): a "
                   "double-close / double-decrement / double-reopen")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in _iter_functions(ctx.tree):
            findings.extend(self._check_fn(fn, ctx))
        return findings

    def _check_fn(self, fn, ctx: ModuleContext) -> List[Finding]:
        quick = _quick_events(fn)
        releases = [e.rid for e in quick if e.action == "release"]
        # two releases of one resource are the cheapest possible
        # precondition for a double-release
        if len(releases) < 2 or len(set(releases)) == len(releases):
            return []
        cfg = build_cfg(fn)
        events = _cfg_events(cfg)
        if not events:
            return []
        rids = {e.rid for evs in events.values() for e in evs
                if e.action == "release"}
        if not rids:
            return []

        def transfer(node: CFGNode, state):
            for e in events.get(node.index, ()):
                if e.rid not in rids:
                    continue
                fact = "rel:" + e.rid
                if e.action == "release":
                    state = state | {fact}
                else:
                    state = state - {fact}
            return state

        results = solve_forward(cfg, transfer, may=False)
        findings: List[Finding] = []
        for node in cfg.stmt_nodes():
            if node not in results:
                continue
            in_state = results[node][0]
            for e in events.get(node.index, ()):
                if e.action == "release" and ("rel:" + e.rid) in in_state:
                    findings.append(Finding(
                        rule=self.name, severity=self.severity,
                        path=ctx.relpath, line=e.lineno, col=0,
                        message=f"{e.kind} {e.rid!r} is already "
                                f"{_RELEASE_VERBS[e.kind]} on every path "
                                f"reaching this second release in "
                                f"{fn.name!r}",
                        code=ctx.source_line(e.lineno)))
        return findings


# -- declarative paired-call protocol specs --------------------------------

@dataclasses.dataclass(frozen=True)
class ProtocolSpec:
    """One pairing contract.  ``begin``/``settle`` entries are call
    matchers: ``"name"`` matches any call whose callee's terminal name is
    ``name``; ``"name:literal"`` additionally requires a string-literal
    argument equal to ``literal`` (so two admin verbs of the same callee
    are distinct protocol events).

    ``kind="settle"``: after a *begin* call, every path to a function
    exit must pass a *settle* call.  ``kind="precede"``: every *begin*
    call must have a *settle* call behind it on ALL incoming paths (the
    barrier-before-action form).  ``scope`` restricts the spec to files
    whose directory path contains one of the components (empty: all)."""

    name: str
    begin: Tuple[str, ...]
    settle: Tuple[str, ...]
    description: str
    kind: str = "settle"
    scope: Tuple[str, ...] = ()


#: The registered protocols.  New subsystems add a row here (and a
#: fixture pair under tests/data/lint_fixtures/) — not a new rule class.
PROTOCOL_SPECS: Tuple[ProtocolSpec, ...] = (
    ProtocolSpec(
        name="staged-reload",
        begin=("stage_reload",),
        settle=("commit_staged", "abort_staged"),
        description="a staged param tree must be committed or aborted on "
                    "every path — a stranded stage is a full device-tree "
                    "leak and a stale-commit hazard (PR 7)",
    ),
    ProtocolSpec(
        name="rollout-prepare",
        begin=("_admin:prepare",),
        settle=("_abort", "_admin:commit", "_admin:rollback",
                "_admin:abort"),
        description="every replica the rollout coordinator prepared must "
                    "be committed, rolled back, or aborted before the "
                    "coordinator returns (PR 7: a router-side timeout "
                    "with engine-side success stranded a staged tree)",
        scope=("serving",),
    ),
    ProtocolSpec(
        name="deploy-lifecycle",
        begin=("begin_shadow", "begin_canary"),
        settle=("promote", "rollback", "abort"),
        description="a started shadow/canary deploy must reach "
                    "promote/rollback/abort on every path — an unsettled "
                    "candidate is a resident device param tree leak AND "
                    "leaves live traffic split against a version nobody "
                    "is evaluating (the PR 7 stranded-staged-tree class, "
                    "at deploy granularity)",
        scope=("serving",),
    ),
    ProtocolSpec(
        name="spill-after-drain",
        kind="precede",
        begin=("spill",),
        settle=("wait_for",),
        description="a session spill must happen behind the in-flight "
                    "drain barrier: an acknowledged frame's state must be "
                    "in the spill (PR 10)",
        scope=("serving",),
    ),
)


def _parse_matcher(entry: str) -> Tuple[str, Optional[str]]:
    if ":" in entry:
        name, lit = entry.split(":", 1)
        return name, lit
    return entry, None


def _call_matches(call: ast.Call, entry: str) -> bool:
    name, lit = _parse_matcher(entry)
    if terminal_name(call.func) != name:
        return False
    if lit is None:
        return True
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Constant) and arg.value == lit:
            return True
    return False


def _protocol_calls(stmt: ast.stmt, entries: Sequence[str]
                    ) -> List[ast.Call]:
    out: List[ast.Call] = []
    for expr in header_exprs(stmt):
        for node in _walk_no_scopes(expr):
            if isinstance(node, ast.Call) and any(
                    _call_matches(node, e) for e in entries):
                out.append(node)
    return out


class PairedCallRule(Rule):
    name = "proto-paired-call"
    severity = "error"
    description = ("a protocol's begin call has a path that never settles "
                   "it (stage without commit/abort, action without its "
                   "barrier) — see PROTOCOL_SPECS in rules_paths.py")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        dirs = ctx.relpath.split("/")[:-1]
        specs = [s for s in PROTOCOL_SPECS
                 if not s.scope or any(d in dirs for d in s.scope)]
        if not specs:
            return []
        # cheap module-level pre-scan: a spec whose begin callee is never
        # even named in the source can't fire in any function
        specs = [s for s in specs
                 if any(_parse_matcher(e)[0] in ctx.source
                        for e in s.begin)]
        if not specs:
            return []
        findings: List[Finding] = []
        for fn in _iter_functions(ctx.tree):
            called = {terminal_name(n.func) for n in ast.walk(fn)
                      if isinstance(n, ast.Call)}
            for spec in specs:
                if not any(_parse_matcher(e)[0] in called
                           for e in spec.begin):
                    continue
                findings.extend(self._check_fn(fn, spec, ctx))
        return findings

    def _check_fn(self, fn, spec: ProtocolSpec, ctx: ModuleContext
                  ) -> List[Finding]:
        begin_nodes: List[Tuple[CFGNode, int]] = []
        cfg = build_cfg(fn)
        settle_idx: Set[int] = set()
        for node in cfg.stmt_nodes():
            if node.kind == "handler":
                continue
            calls = _protocol_calls(node.stmt, spec.begin)
            if calls:
                begin_nodes.append((node, calls[0].lineno))
            if _protocol_calls(node.stmt, spec.settle):
                settle_idx.add(node.index)
        if not begin_nodes:
            return []

        if spec.kind == "precede":
            # must-analysis: the barrier fact holds only when EVERY path
            # into the action has passed a settle call
            def transfer(node: CFGNode, state):
                if node.index in settle_idx:
                    return state | {"barrier"}
                return state

            results = solve_forward(cfg, transfer, may=False,
                                    exc_transfer=lambda n, s: s)
            out: List[Finding] = []
            for node, line in begin_nodes:
                if node in results and "barrier" not in results[node][0]:
                    out.append(Finding(
                        rule=self.name, severity=self.severity,
                        path=ctx.relpath, line=line, col=0,
                        message=f"protocol {spec.name!r}: this call must "
                                f"be behind {'/'.join(spec.settle)} on "
                                f"every path — {spec.description}",
                        code=ctx.source_line(line)))
            return out

        # settle kind: may-analysis of the unsettled fact
        begin_idx = {n.index for n, _ in begin_nodes}

        def transfer(node: CFGNode, state):
            # a node that both settles and begins (retry shapes) begins
            if node.index in settle_idx:
                state = state - {"pending"}
            if node.index in begin_idx:
                state = state | {"pending"}
            return state

        def exc_transfer(node: CFGNode, state):
            # a begin call that raises began nothing; a settle on the
            # same node still settles
            if node.index in settle_idx:
                state = state - {"pending"}
            return state

        results = solve_forward(cfg, transfer, may=True,
                                exc_transfer=exc_transfer)
        out = []
        for node, line in begin_nodes:
            leaks = []
            for exit_node, what in ((cfg.raise_exit, "an exception path"),
                                    (cfg.exit, "a return path")):
                if exit_node in results and \
                        "pending" in results[exit_node][0]:
                    path = witness_path(cfg, results, "pending", node,
                                        exit_node)
                    if path:
                        concrete = [n for n in path[1:-1]
                                    if n.lineno is not None]
                        via = (f" via line {concrete[-1].lineno}"
                               if concrete else "")
                        leaks.append(what + via)
            if leaks:
                out.append(Finding(
                    rule=self.name, severity=self.severity,
                    path=ctx.relpath, line=line, col=0,
                    message=f"protocol {spec.name!r}: begun here but "
                            f"{' and '.join(leaks)} reach exit without "
                            f"{'/'.join(_parse_matcher(s)[0] for s in spec.settle)}"
                            f" — {spec.description}",
                    code=ctx.source_line(line)))
        return out


PATH_RULES = (ResourceLeakRule, PairedCallRule, DoubleReleaseRule)
