"""glomlint observability rule pack.

  * ``obs-debug-in-cache`` — the fleet-observatory boundary (PR 9): the
    ``/debug/*`` pull plane (trace rings, forensics manifests, fleet
    timeline) lives in the HTTP fronts and is POLLED by the collector;
    ``serving/compile_cache.py`` is the request path's execute core,
    where every millisecond is a served millisecond.  A debug-endpoint
    reference or an HTTP client import appearing there means the data
    plane grew a dependency on the observability plane — the exact
    coupling the pull topology exists to forbid (a slow observer must
    never be able to slow a request).
"""

from __future__ import annotations

import ast
from typing import List

from glom_tpu.analysis.engine import Finding, ModuleContext, Rule, dotted_name

_HTTP_CLIENT_ROOTS = {"urllib", "http", "requests", "socket"}


class DebugPlaneInCacheRule(Rule):
    name = "obs-debug-in-cache"
    severity = "error"
    description = ("/debug/* endpoint reference or HTTP client inside "
                   "serving/compile_cache.py — the execute core must "
                   "never touch the observability pull plane")

    TARGET_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring (the request-path-compile rule's
        # convention): only serving/compile_cache.py is in scope
        if (self.SCOPE_DIR not in parts[:-1]
                or parts[-1] != self.TARGET_BASENAME):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value.startswith("/debug")):
                findings.append(ctx.finding(
                    self, node,
                    f"debug-plane endpoint {node.value!r} referenced in "
                    f"the execute core: /debug/* is pulled by the "
                    f"observatory from the HTTP fronts, never from the "
                    f"request path"))
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                       else "")
                roots = ([mod.split(".")[0]] if mod
                         else [a.name.split(".")[0] for a in node.names])
                for root in roots:
                    if root in _HTTP_CLIENT_ROOTS:
                        findings.append(ctx.finding(
                            self, node,
                            f"HTTP/network import {root!r} in the execute "
                            f"core: network I/O (a /debug pull, a metrics "
                            f"push) has no place on the request path"))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d and d.split(".")[0] in {"urllib", "requests"}:
                    findings.append(ctx.finding(
                        self, node,
                        f"network call {d}(...) in the execute core: the "
                        f"observability plane pulls; the data plane never "
                        f"calls out"))
        return findings


OBS_RULES = (DebugPlaneInCacheRule,)
