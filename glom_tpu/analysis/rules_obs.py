"""glomlint observability rule pack.

  * ``obs-debug-in-cache`` — the fleet-observatory boundary (PR 9): the
    ``/debug/*`` pull plane (trace rings, forensics manifests, fleet
    timeline) lives in the HTTP fronts and is POLLED by the collector;
    ``serving/compile_cache.py`` is the request path's execute core,
    where every millisecond is a served millisecond.  A debug-endpoint
    reference or an HTTP client import appearing there means the data
    plane grew a dependency on the observability plane — the exact
    coupling the pull topology exists to forbid (a slow observer must
    never be able to slow a request).  The quality plane (PR 17) rides
    the same boundary: ``glom_tpu.obs.quality``/``glom_tpu.obs.sketch``
    imports are equally forbidden here — the quality post-pass is a
    SEPARATE bucketed cache owned by the engine, attached outside the
    execute core, so sketch bookkeeping can never ride a request.

  * ``obs-state-in-cache`` — the session-state boundary (PR 10): per-
    session column state is OWNED by :mod:`glom_tpu.serving.sessions`
    and threaded through the compile cache as an opaque array.  The
    cache must stay a pure ``shape -> executable`` map: a session-store
    import, a ``SessionStore`` reference, or a store mutation call
    (``.put``/``.reset``/``.spill``/...) inside ``compile_cache.py``
    would put TTL/LRU/byte accounting — locks, eviction sweeps,
    spill I/O — onto the execute core's hot path, and make the one
    jit-owning module stateful (its executables could then differ by
    WHEN they ran, the property the AOT warmup contract forbids).

  * ``obs-unbounded-series`` — the retention contract (PR 16): every
    per-sample/per-request accumulator in ``glom_tpu/obs/`` must be
    bounded — ``deque(maxlen=)``, an explicit ``len()`` cap check, or
    an eviction call (``pop``/``popleft``/``popitem``/``clear``/
    ``del``) somewhere in the owning class.  The TSDB and trace/
    forensics rings exist to watch long-lived serving processes for
    leaks; an unbounded list inside them IS the leak, discovered only
    after days of uptime.
"""

from __future__ import annotations

import ast
from typing import List

from glom_tpu.analysis.engine import Finding, ModuleContext, Rule, dotted_name

_HTTP_CLIENT_ROOTS = {"urllib", "http", "requests", "socket"}

#: obs quality-plane modules forbidden in the execute core: the sampled
#: post-pass lives in the ENGINE's separate quality cache, never here
_QUALITY_PLANE_LEAVES = {"quality", "sketch"}


class DebugPlaneInCacheRule(Rule):
    name = "obs-debug-in-cache"
    severity = "error"
    description = ("/debug/* endpoint reference or HTTP client inside "
                   "serving/compile_cache.py — the execute core must "
                   "never touch the observability pull plane")

    TARGET_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring (the request-path-compile rule's
        # convention): only serving/compile_cache.py is in scope
        if (self.SCOPE_DIR not in parts[:-1]
                or parts[-1] != self.TARGET_BASENAME):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value.startswith("/debug")):
                findings.append(ctx.finding(
                    self, node,
                    f"debug-plane endpoint {node.value!r} referenced in "
                    f"the execute core: /debug/* is pulled by the "
                    f"observatory from the HTTP fronts, never from the "
                    f"request path"))
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                       else "")
                mods = [mod] if mod else [a.name for a in node.names]
                for dotted in mods:
                    root = dotted.split(".")[0]
                    if root in _HTTP_CLIENT_ROOTS:
                        findings.append(ctx.finding(
                            self, node,
                            f"HTTP/network import {root!r} in the execute "
                            f"core: network I/O (a /debug pull, a metrics "
                            f"push) has no place on the request path"))
                    parts_mod = dotted.split(".")
                    if ("obs" in parts_mod
                            and parts_mod[-1] in _QUALITY_PLANE_LEAVES):
                        findings.append(ctx.finding(
                            self, node,
                            f"quality-plane import {dotted!r} in the "
                            f"execute core: sketch/quality bookkeeping "
                            f"belongs to the engine's separate sampled "
                            f"post-pass cache, never the request path"))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d and d.split(".")[0] in {"urllib", "requests"}:
                    findings.append(ctx.finding(
                        self, node,
                        f"network call {d}(...) in the execute core: the "
                        f"observability plane pulls; the data plane never "
                        f"calls out"))
        return findings


_STORE_MUTATORS = {"put", "reset", "sweep", "spill", "restore", "pop",
                   "clear", "update", "note_session"}


class SessionStateInCacheRule(Rule):
    name = "obs-state-in-cache"
    severity = "error"
    description = ("session-store reference or mutation inside "
                   "serving/compile_cache.py — the execute core threads "
                   "state as an opaque array; the state plane (TTL/LRU/"
                   "spill bookkeeping) must never enter the hot path")

    TARGET_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    @staticmethod
    def _names_session(dotted: str) -> bool:
        return any("session" in part.lower() for part in dotted.split("."))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring (the obs-debug-in-cache
        # convention): only serving/compile_cache.py is in scope
        if (self.SCOPE_DIR not in parts[:-1]
                or parts[-1] != self.TARGET_BASENAME):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                       else "")
                names = [a.name for a in node.names]
                dotted_all = ([mod] if mod else []) + names
                if any("sessions" in d.split(".") or "SessionStore" in d
                       for d in dotted_all):
                    findings.append(ctx.finding(
                        self, node,
                        "session-store import in the execute core: the "
                        "cache receives state as an opaque argument from "
                        "the engine; it must not know the store exists"))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if (d and "." in d
                        and d.rsplit(".", 1)[1] in _STORE_MUTATORS
                        and self._names_session(d.rsplit(".", 1)[0])):
                    findings.append(ctx.finding(
                        self, node,
                        f"session-store mutation {d}(...) in the execute "
                        f"core: store bookkeeping (locks, eviction, "
                        f"spill I/O) has no place on the request path — "
                        f"the ENGINE owns get/put around the cache call"))
            elif isinstance(node, ast.Name) and node.id == "SessionStore":
                findings.append(ctx.finding(
                    self, node,
                    "SessionStore referenced in the execute core: the "
                    "cache must stay a pure shape -> executable map"))
        return findings


#: growth calls that accumulate one element per invocation
_GROWTH_METHODS = {"append", "extend", "appendleft", "add"}
#: eviction calls that count as bounding evidence for an attribute
_EVICT_METHODS = {"pop", "popleft", "popitem", "clear"}
#: constructors whose result is unbounded by default
_UNBOUNDED_CTORS = {"list", "dict", "set", "OrderedDict", "defaultdict"}


def _self_attr(node) -> str:
    """``self.X`` -> ``"X"``, else ``""``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


class UnboundedSeriesRule(Rule):
    name = "obs-unbounded-series"
    severity = "error"
    description = ("per-sample container in glom_tpu/obs/ grows without a "
                   "deque(maxlen=), len() cap check, or eviction call — "
                   "the telemetry plane must not become the memory leak "
                   "it exists to detect")

    SCOPE_DIR = "obs"

    @staticmethod
    def _unbounded_init(value) -> bool:
        """Is this initializer an unbounded container?  Literal displays
        and comprehensions, the stdlib container constructors, and
        ``deque()`` WITHOUT ``maxlen=`` all qualify."""
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            d = dotted_name(value.func) or ""
            base = d.split(".")[-1]
            if base == "deque":
                return not any(kw.arg == "maxlen" for kw in value.keywords)
            return base in _UNBOUNDED_CTORS
        return False

    def _class_findings(self, ctx: ModuleContext,
                        cls: ast.ClassDef) -> List[Finding]:
        unbounded: dict = {}     # attr -> init node
        evidence: set = set()    # attrs with cap/eviction anywhere in class
        growth: List = []        # (attr, node, kind)
        for node in ast.walk(cls):
            # self.X = <unbounded container> (chained targets included)
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr and self._unbounded_init(node.value):
                        unbounded.setdefault(attr, node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                attr = _self_attr(node.target)
                if attr and self._unbounded_init(node.value):
                    unbounded.setdefault(attr, node)
            # del self.X[...] is eviction
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            evidence.add(attr)
            elif isinstance(node, ast.Call):
                # len(self.X) anywhere reads as a cap check (the
                # `if len(self._series) >= self.max_series: drop` shape)
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "len" and node.args):
                    attr = _self_attr(node.args[0])
                    if attr:
                        evidence.add(attr)
                elif isinstance(node.func, ast.Attribute):
                    attr = _self_attr(node.func.value)
                    if attr and node.func.attr in _EVICT_METHODS:
                        evidence.add(attr)
        # growth sites: .append()-family calls in any method but
        # __init__, and subscript stores inside a loop (the per-sample
        # shapes); a one-off subscript store outside a loop is a keyed
        # update, not accumulation
        for method in cls.body:
            if (not isinstance(method,
                               (ast.FunctionDef, ast.AsyncFunctionDef))
                    or method.name == "__init__"):
                continue
            for node in ast.walk(method):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _GROWTH_METHODS):
                    attr = _self_attr(node.func.value)
                    if attr:
                        growth.append((attr, node, node.func.attr))
                elif isinstance(node, (ast.For, ast.While)):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Assign):
                            for target in sub.targets:
                                if isinstance(target, ast.Subscript):
                                    attr = _self_attr(target.value)
                                    if attr:
                                        growth.append(
                                            (attr, sub, "loop store"))
        findings: List[Finding] = []
        flagged: set = set()
        for attr, node, kind in growth:
            if attr not in unbounded or attr in evidence or attr in flagged:
                continue
            flagged.add(attr)
            findings.append(ctx.finding(
                self, node,
                f"self.{attr} grows per sample ({kind}) but is initialized "
                f"unbounded and class {cls.name} never caps or evicts it — "
                f"use deque(maxlen=), a len() bound, or an eviction sweep "
                f"(the TSDB retention contract)"))
        return findings

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match (the obs-debug-in-cache convention): only
        # modules under an obs/ directory are in scope — the telemetry
        # plane's own retention contract, not a repo-wide style rule
        if self.SCOPE_DIR not in parts[:-1]:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._class_findings(ctx, node))
        return findings


OBS_RULES = (DebugPlaneInCacheRule, SessionStateInCacheRule,
             UnboundedSeriesRule)
