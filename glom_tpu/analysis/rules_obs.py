"""glomlint observability rule pack.

  * ``obs-debug-in-cache`` — the fleet-observatory boundary (PR 9): the
    ``/debug/*`` pull plane (trace rings, forensics manifests, fleet
    timeline) lives in the HTTP fronts and is POLLED by the collector;
    ``serving/compile_cache.py`` is the request path's execute core,
    where every millisecond is a served millisecond.  A debug-endpoint
    reference or an HTTP client import appearing there means the data
    plane grew a dependency on the observability plane — the exact
    coupling the pull topology exists to forbid (a slow observer must
    never be able to slow a request).

  * ``obs-state-in-cache`` — the session-state boundary (PR 10): per-
    session column state is OWNED by :mod:`glom_tpu.serving.sessions`
    and threaded through the compile cache as an opaque array.  The
    cache must stay a pure ``shape -> executable`` map: a session-store
    import, a ``SessionStore`` reference, or a store mutation call
    (``.put``/``.reset``/``.spill``/...) inside ``compile_cache.py``
    would put TTL/LRU/byte accounting — locks, eviction sweeps,
    spill I/O — onto the execute core's hot path, and make the one
    jit-owning module stateful (its executables could then differ by
    WHEN they ran, the property the AOT warmup contract forbids).
"""

from __future__ import annotations

import ast
from typing import List

from glom_tpu.analysis.engine import Finding, ModuleContext, Rule, dotted_name

_HTTP_CLIENT_ROOTS = {"urllib", "http", "requests", "socket"}


class DebugPlaneInCacheRule(Rule):
    name = "obs-debug-in-cache"
    severity = "error"
    description = ("/debug/* endpoint reference or HTTP client inside "
                   "serving/compile_cache.py — the execute core must "
                   "never touch the observability pull plane")

    TARGET_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring (the request-path-compile rule's
        # convention): only serving/compile_cache.py is in scope
        if (self.SCOPE_DIR not in parts[:-1]
                or parts[-1] != self.TARGET_BASENAME):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value.startswith("/debug")):
                findings.append(ctx.finding(
                    self, node,
                    f"debug-plane endpoint {node.value!r} referenced in "
                    f"the execute core: /debug/* is pulled by the "
                    f"observatory from the HTTP fronts, never from the "
                    f"request path"))
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                       else "")
                roots = ([mod.split(".")[0]] if mod
                         else [a.name.split(".")[0] for a in node.names])
                for root in roots:
                    if root in _HTTP_CLIENT_ROOTS:
                        findings.append(ctx.finding(
                            self, node,
                            f"HTTP/network import {root!r} in the execute "
                            f"core: network I/O (a /debug pull, a metrics "
                            f"push) has no place on the request path"))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d and d.split(".")[0] in {"urllib", "requests"}:
                    findings.append(ctx.finding(
                        self, node,
                        f"network call {d}(...) in the execute core: the "
                        f"observability plane pulls; the data plane never "
                        f"calls out"))
        return findings


_STORE_MUTATORS = {"put", "reset", "sweep", "spill", "restore", "pop",
                   "clear", "update", "note_session"}


class SessionStateInCacheRule(Rule):
    name = "obs-state-in-cache"
    severity = "error"
    description = ("session-store reference or mutation inside "
                   "serving/compile_cache.py — the execute core threads "
                   "state as an opaque array; the state plane (TTL/LRU/"
                   "spill bookkeeping) must never enter the hot path")

    TARGET_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    @staticmethod
    def _names_session(dotted: str) -> bool:
        return any("session" in part.lower() for part in dotted.split("."))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring (the obs-debug-in-cache
        # convention): only serving/compile_cache.py is in scope
        if (self.SCOPE_DIR not in parts[:-1]
                or parts[-1] != self.TARGET_BASENAME):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                       else "")
                names = [a.name for a in node.names]
                dotted_all = ([mod] if mod else []) + names
                if any("sessions" in d.split(".") or "SessionStore" in d
                       for d in dotted_all):
                    findings.append(ctx.finding(
                        self, node,
                        "session-store import in the execute core: the "
                        "cache receives state as an opaque argument from "
                        "the engine; it must not know the store exists"))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if (d and "." in d
                        and d.rsplit(".", 1)[1] in _STORE_MUTATORS
                        and self._names_session(d.rsplit(".", 1)[0])):
                    findings.append(ctx.finding(
                        self, node,
                        f"session-store mutation {d}(...) in the execute "
                        f"core: store bookkeeping (locks, eviction, "
                        f"spill I/O) has no place on the request path — "
                        f"the ENGINE owns get/put around the cache call"))
            elif isinstance(node, ast.Name) and node.id == "SessionStore":
                findings.append(ctx.finding(
                    self, node,
                    "SessionStore referenced in the execute core: the "
                    "cache must stay a pure shape -> executable map"))
        return findings


OBS_RULES = (DebugPlaneInCacheRule, SessionStateInCacheRule)
