"""glomlint bulk-tier rule pack.

  * ``bulk-isolation`` — the scavenger-class boundary (PR 18): the bulk
    inference tier fills residual bucket padding and idle flush windows
    and must stay INVISIBLE to the online plane.  That invisibility is
    structural, not behavioral: bulk modules (``glom_tpu/bulk/`` and any
    ``bulk.py`` under ``serving/``) must never import the online
    admission, SLO, or tenant-quota machinery — a ``TenantAdmission`` or
    ``SloManager`` reference inside the bulk tier means offline work
    grew a dependency on (or worse, a write path into) the online
    control plane, the exact coupling the scavenger contract forbids
    (bulk slots are never admitted, never quota'd, never SLO'd; they
    ride whatever the online plane already paid for).  The same rule
    enforces the bounded-enqueue half of the contract: every per-slot /
    per-chunk accumulator inside a bulk class must be bounded — a
    ``deque(maxlen=)``, a ``len()`` cap check, or an eviction call — so
    a stalled sink or a paused job can never turn the scavenger into an
    unbounded memory queue riding inside the serving process.
"""

from __future__ import annotations

import ast
from typing import List

from glom_tpu.analysis.engine import Finding, ModuleContext, Rule, dotted_name

#: online-plane modules the bulk tier must never import (module path
#: component match on the dotted name)
_FORBIDDEN_MODULES = {
    # SLO plane: bulk work is invisible to online SLOs by contract
    ("obs", "slo"),
}

#: online admission / quota symbols forbidden in bulk modules wherever
#: they are imported from
_FORBIDDEN_SYMBOLS = {
    "TenantAdmission", "TenantQuotaExceeded", "TokenBucket",
    "parse_quota", "SloManager", "parse_slo",
}

#: growth calls that accumulate one element per invocation
_GROWTH_METHODS = {"append", "extend", "appendleft", "add"}
#: eviction calls that count as bounding evidence for an attribute
_EVICT_METHODS = {"pop", "popleft", "popitem", "clear"}
#: constructors whose result is unbounded by default
_UNBOUNDED_CTORS = {"list", "dict", "set", "OrderedDict", "defaultdict"}


def _self_attr(node) -> str:
    """``self.X`` -> ``"X"``, else ``""``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


class BulkIsolationRule(Rule):
    name = "bulk-isolation"
    severity = "error"
    description = ("bulk-tier module imports online admission/SLO/quota "
                   "machinery, or grows an unbounded enqueue buffer — "
                   "the scavenger class must stay invisible to the "
                   "online plane and bounded in memory")

    @staticmethod
    def _in_scope(relpath: str) -> bool:
        # component match, not substring (the obs-debug-in-cache
        # convention): glom_tpu/bulk/* and any bulk.py module are the
        # bulk tier; tests and fixtures resolve their own relpaths
        parts = relpath.split("/")
        return "bulk" in parts[:-1] or parts[-1] == "bulk.py"

    # -- forbidden-import half -------------------------------------

    def _import_findings(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                   else "")
            names = [a.name for a in node.names]
            dotted_all = ([mod] if mod else []) + names
            for dotted in dotted_all:
                parts = dotted.split(".")
                for tail in _FORBIDDEN_MODULES:
                    n = len(tail)
                    if any(tuple(parts[i:i + n]) == tail
                           for i in range(len(parts) - n + 1)):
                        findings.append(ctx.finding(
                            self, node,
                            f"online-plane import {dotted!r} in a bulk "
                            f"module: the scavenger tier is invisible to "
                            f"online SLOs by contract — it must not even "
                            f"know the SLO plane exists"))
            for sym in _FORBIDDEN_SYMBOLS & set(names):
                findings.append(ctx.finding(
                    self, node,
                    f"admission/quota symbol {sym!r} imported into a "
                    f"bulk module: bulk slots are never admitted, "
                    f"quota'd, or SLO'd — they fill padding the online "
                    f"plane already paid for"))
        return findings

    # -- bounded-enqueue half (the obs-unbounded-series machinery,
    #    scoped to bulk classes) -----------------------------------

    @staticmethod
    def _unbounded_init(value) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            d = dotted_name(value.func) or ""
            base = d.split(".")[-1]
            if base == "deque":
                return not any(kw.arg == "maxlen" for kw in value.keywords)
            return base in _UNBOUNDED_CTORS
        return False

    def _class_findings(self, ctx: ModuleContext,
                        cls: ast.ClassDef) -> List[Finding]:
        unbounded: dict = {}     # attr -> init node
        evidence: set = set()    # attrs with cap/eviction anywhere in class
        growth: List = []        # (attr, node, kind)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr and self._unbounded_init(node.value):
                        unbounded.setdefault(attr, node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                attr = _self_attr(node.target)
                if attr and self._unbounded_init(node.value):
                    unbounded.setdefault(attr, node)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            evidence.add(attr)
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "len" and node.args):
                    attr = _self_attr(node.args[0])
                    if attr:
                        evidence.add(attr)
                elif isinstance(node.func, ast.Attribute):
                    attr = _self_attr(node.func.value)
                    if attr and node.func.attr in _EVICT_METHODS:
                        evidence.add(attr)
        for method in cls.body:
            if (not isinstance(method,
                               (ast.FunctionDef, ast.AsyncFunctionDef))
                    or method.name == "__init__"):
                continue
            for node in ast.walk(method):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _GROWTH_METHODS):
                    attr = _self_attr(node.func.value)
                    if attr:
                        growth.append((attr, node, node.func.attr))
        findings: List[Finding] = []
        flagged: set = set()
        for attr, node, kind in growth:
            if attr not in unbounded or attr in evidence or attr in flagged:
                continue
            flagged.add(attr)
            findings.append(ctx.finding(
                self, node,
                f"self.{attr} enqueues per slot ({kind}) but is "
                f"initialized unbounded and class {cls.name} never caps "
                f"or evicts it — a stalled sink would turn the scavenger "
                f"into an unbounded queue inside the serving process; "
                f"use deque(maxlen=), a len() bound, or an eviction "
                f"sweep"))
        return findings

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not self._in_scope(ctx.relpath):
            return []
        findings = self._import_findings(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._class_findings(ctx, node))
        return findings


BULK_RULES = (BulkIsolationRule,)
