"""glomlint JAX/TPU rule pack — each rule encodes a bug this repo shipped.

  * ``jax-donation-aliasing`` — the PR 6 SIGABRT: a ``donate_argnums``
    jit fed a numpy/npz-loaded tree.  On CPU the jit feed can zero-copy
    alias the numpy heap allocation; donation then has XLA free memory
    numpy still owns ("corrupted double-linked list", reliably fatal
    under persistent-cache-deserialized executables).  Trainer.restore
    now launders restored trees through a non-donating jit identity —
    this rule keeps the next npz-into-donating-jit from shipping.
  * ``jax-request-path-compile`` — the serving contract since PR 3: the
    request path never compiles; all jit/lower/compile lives in
    ``serving/compile_cache.py`` (AOT warmup).  A jit anywhere else under
    ``serving/`` is a latency cliff waiting for the first unlucky request.
  * ``jax-host-sync`` — ``float()`` / ``np.asarray()`` /
    ``.block_until_ready()`` / ``jax.device_get`` inside the measured hot
    paths (``_fit_loop``, the batcher, the execute path) stalls the
    device pipeline; PR 1's phase-timed loop exists precisely because
    untracked host syncs were eating step time.
  * ``jax-traced-if`` — Python ``if`` on a traced value inside a jitted
    function: TracerBoolConversionError at best, silent per-shape
    recompile at worst (the recompile monitor's whole reason to exist).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from glom_tpu.analysis.engine import (
    Finding, ModuleContext, Rule, child_blocks, dotted_name, is_compound,
    parent_map, terminal_name,
)

_JIT_NAMES = {"jax.jit", "jax.pjit", "pjit", "jit"}
_NUMPY_ROOTS = {"np", "numpy", "onp"}
_NUMPY_HOST_FUNCS = {"load", "asarray", "array", "frombuffer", "fromfile",
                     "copy", "ascontiguousarray"}


def _donated_indices(call: ast.Call) -> Set[int]:
    """Donated positional indices of a ``jax.jit(...)`` call; non-literal
    ``donate_argnums`` (e.g. ``(0,) if donate else ()``) conservatively
    reads as ``{0}``."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            idxs = {e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)}
            return idxs  # empty literal () donates nothing
        return {0}
    return set()


class DonationAliasingRule(Rule):
    name = "jax-donation-aliasing"
    severity = "error"
    description = ("numpy/npz-loaded tree fed to a donate_argnums jit "
                   "(PR 6 double-free SIGABRT); launder through a "
                   "non-donating jit identity first")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        donating: Dict[str, Set[int]] = {}
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func) in _JIT_NAMES):
                idxs = _donated_indices(node.value)
                tgt = terminal_name(node.targets[0])
                if idxs and tgt:
                    donating[tgt] = idxs
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if (isinstance(dec, ast.Call)
                            and dotted_name(dec.func) in _JIT_NAMES):
                        idxs = _donated_indices(dec)
                        if idxs:
                            donating[node.name] = idxs
        if not donating:
            return []
        findings: List[Finding] = []
        # module scope, then each function scope with fresh taint
        self._scan_body(ctx.tree.body, set(), donating, ctx, findings)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_body(node.body, set(), donating, ctx, findings)
        return findings

    # -- intra-scope, statement-ordered taint tracking ---------------------
    def _tainted(self, e: ast.AST, taint: Set[str]) -> bool:
        if isinstance(e, ast.Name):
            return e.id in taint
        if isinstance(e, ast.Call):
            d = dotted_name(e.func)
            if d:
                parts = d.split(".")
                if (len(parts) >= 2 and parts[0] in _NUMPY_ROOTS
                        and parts[-1] in _NUMPY_HOST_FUNCS):
                    return True
                if d == "dict":
                    return any(self._tainted(a, taint) for a in e.args)
            return False
        if isinstance(e, (ast.Subscript, ast.Attribute, ast.Starred)):
            return self._tainted(e.value, taint)
        if isinstance(e, ast.Dict):
            return any(v is not None and self._tainted(v, taint)
                       for v in e.values)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self._tainted(v, taint) for v in e.elts)
        if isinstance(e, ast.IfExp):
            return (self._tainted(e.body, taint)
                    or self._tainted(e.orelse, taint))
        return False

    def _check_calls(self, root: ast.AST, taint: Set[str],
                     donating: Dict[str, Set[int]], ctx: ModuleContext,
                     findings: List[Finding]) -> None:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            callee = terminal_name(node.func)
            if callee not in donating:
                continue
            for i in donating[callee]:
                if i < len(node.args) and self._tainted(node.args[i], taint):
                    findings.append(ctx.finding(
                        self, node,
                        f"argument {i} of donating jit {callee!r} derives "
                        f"from a numpy/npz host buffer — donation frees "
                        f"memory numpy owns; launder through a "
                        f"non-donating jit identity first"))

    def _scan_body(self, body: List[ast.stmt], taint: Set[str],
                   donating: Dict[str, Set[int]], ctx: ModuleContext,
                   findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if not is_compound(stmt):
                # simple statement: full walk with the current taint
                self._check_calls(stmt, taint, donating, ctx, findings)
                if isinstance(stmt, ast.Assign):
                    is_tainted = self._tainted(stmt.value, taint)
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            (taint.add if is_tainted else taint.discard)(tgt.id)
                elif (isinstance(stmt, ast.AnnAssign)
                        and stmt.value is not None
                        and isinstance(stmt.target, ast.Name)):
                    is_tainted = self._tainted(stmt.value, taint)
                    (taint.add if is_tainted else taint.discard)(stmt.target.id)
                continue
            # compound statement: check only header expressions here, then
            # scan each branch from a COPY of the incoming taint and union
            # the results — one branch's clean reassignment must not erase
            # another branch's taint (the if-resuming/else-init restore
            # pattern is exactly the PR 6 shape)
            for field in ("test", "iter", "subject"):
                expr = getattr(stmt, field, None)
                if isinstance(expr, ast.AST):
                    self._check_calls(expr, taint, donating, ctx, findings)
            for item in getattr(stmt, "items", []) or []:
                self._check_calls(item.context_expr, taint, donating, ctx,
                                  findings)
            merged: Set[str] = set()
            for block in child_blocks(stmt):
                branch_taint = set(taint)
                self._scan_body(block, branch_taint, donating, ctx,
                                findings)
                merged |= branch_taint
            taint |= merged


class RequestPathCompileRule(Rule):
    name = "jax-request-path-compile"
    severity = "error"
    description = ("jit/lower/compile under serving/ outside "
                   "compile_cache.py — the request path never compiles "
                   "(AOT warmup owns every executable)")

    ALLOWED_BASENAME = "compile_cache.py"
    SCOPE_DIR = "serving"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.split("/")
        # component match, not substring: observing/ is not serving/
        if self.SCOPE_DIR not in parts[:-1] or parts[-1] == self.ALLOWED_BASENAME:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d in _JIT_NAMES:
                findings.append(ctx.finding(
                    self, node,
                    f"{d}(...) in a serving module: only "
                    f"{self.ALLOWED_BASENAME} may build executables "
                    f"(AOT warmup); anything else can reach the request "
                    f"path"))
            elif isinstance(node.func, ast.Attribute):
                recv = ast.unparse(node.func.value).lower()
                if (node.func.attr == "lower" and "jit" in recv) or (
                        node.func.attr == "compile" and "lower" in recv):
                    findings.append(ctx.finding(
                        self, node,
                        f"{ast.unparse(node.func)}(...) in a serving "
                        f"module: compile steps belong to "
                        f"{self.ALLOWED_BASENAME}'s AOT warmup"))
        return findings


class HostSyncRule(Rule):
    name = "jax-host-sync"
    severity = "warning"
    description = ("host sync (float()/np.asarray()/.block_until_ready()/"
                   "device_get) inside a measured hot path stalls the "
                   "device pipeline")

    #: (relpath suffix, function name) pairs that are latency-critical
    HOT_PATHS: Tuple[Tuple[str, str], ...] = (
        ("training/trainer.py", "_fit_loop"),
        ("serving/batcher.py", "submit"),
        ("serving/batcher.py", "next_batch"),
        ("serving/batcher.py", "_take_batch"),
        ("serving/batcher.py", "_flush_reason"),
        ("serving/compile_cache.py", "__call__"),
        ("serving/engine.py", "_execute_batch"),
        ("serving/engine.py", "_worker_loop"),
    )
    SYNC_ATTRS = {"block_until_ready", "item"}
    SYNC_DOTTED = {"jax.device_get"}

    def _is_sync_call(self, node: ast.Call) -> Optional[str]:
        d = dotted_name(node.func)
        if d == "float":
            if node.args and not isinstance(node.args[0], ast.Constant):
                return "float()"
            return None
        if d in self.SYNC_DOTTED:
            return d
        if d:
            parts = d.split(".")
            if (len(parts) >= 2 and parts[0] in _NUMPY_ROOTS
                    and parts[-1] in {"asarray", "array"}):
                return d
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in self.SYNC_ATTRS):
            return "." + node.func.attr + "()"
        return None

    def check(self, ctx: ModuleContext) -> List[Finding]:
        # component-anchored suffix match: preserving/batcher.py must not
        # inherit serving/batcher.py's hot functions
        hot = {fn for suffix, fn in self.HOT_PATHS
               if ("/" + ctx.relpath).endswith("/" + suffix)}
        if not hot:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in hot):
                continue
            seen_lines: Set[int] = set()
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                what = self._is_sync_call(call)
                if what is None or call.lineno in seen_lines:
                    continue
                seen_lines.add(call.lineno)
                findings.append(ctx.finding(
                    self, call,
                    f"{what} inside hot path {node.name!r}: host sync "
                    f"stalls the device pipeline — move it off the "
                    f"request/step path or justify with a suppression"))
        return findings


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _jit_static_names(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """None when ``fn`` is not jit-decorated; else the set of static
    parameter names (``static_argnums``/``static_argnames``)."""
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        call = None
        if dotted_name(dec) in _JIT_NAMES:
            return set()
        if isinstance(dec, ast.Call):
            d = dotted_name(dec.func)
            if d in _JIT_NAMES:
                call = dec
            elif d in {"partial", "functools.partial"} and dec.args and \
                    dotted_name(dec.args[0]) in _JIT_NAMES:
                call = dec
        if call is None:
            continue
        static: Set[str] = set()
        for kw in call.keywords:
            v = kw.value
            if kw.arg == "static_argnames":
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    static.add(v.value)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    static |= {e.value for e in v.elts
                               if isinstance(e, ast.Constant)}
            elif kw.arg == "static_argnums":
                idxs = ([v.value] if isinstance(v, ast.Constant) else
                        [e.value for e in v.elts
                         if isinstance(e, ast.Constant)]
                        if isinstance(v, (ast.Tuple, ast.List)) else [])
                static |= {args[i] for i in idxs
                           if isinstance(i, int) and i < len(args)}
        return static
    return None


class TracedIfRule(Rule):
    name = "jax-traced-if"
    severity = "error"
    description = ("Python `if` on a traced value inside a jitted fn: "
                   "TracerBoolConversionError or a silent per-value "
                   "recompile; use lax.cond / jnp.where")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static = _jit_static_names(fn)
            if static is None:
                continue
            traced = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)} - static - {"self"}
            for node in ast.walk(fn):
                if not isinstance(node, ast.If):
                    continue
                offender = self._traced_test(node.test, traced)
                if offender is not None:
                    findings.append(ctx.finding(
                        self, node,
                        f"`if` on traced parameter {offender!r} inside "
                        f"jitted {fn.name!r}: trace-time Python control "
                        f"flow — use jax.lax.cond/select or mark the "
                        f"argument static"))
        return findings

    def _traced_test(self, test: ast.AST, traced: Set[str]) -> Optional[str]:
        parents = parent_map(test)
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name) and node.id in traced
                    and isinstance(node.ctx, ast.Load)):
                continue
            parent = parents.get(node)
            # static facts about a traced array are fine in Python `if`
            if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
                continue
            if (isinstance(parent, ast.Call)
                    and dotted_name(parent.func) in {"isinstance", "len",
                                                     "type", "id"}):
                continue
            if isinstance(parent, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in parent.ops):
                continue
            return node.id
        return None


JAX_RULES = (DonationAliasingRule, RequestPathCompileRule, HostSyncRule,
             TracedIfRule)
