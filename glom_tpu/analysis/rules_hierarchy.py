"""glomlint part-whole (hierarchy) rule pack.

  * ``hierarchy-isolation`` — the similarity-index reader boundary
    (PR 20): ``glom_tpu/hierarchy/index.py`` is the ``/similar`` store's
    read/write side and is deliberately **jax-free and package-free** —
    stdlib + numpy + mmap only, loadable on a deviceless audit host via
    the ``tools/_obsload.py`` stub pattern.  A ``jax`` import there
    drags the whole runtime (and a device registry probe) into every
    offline index audit; a ``glom_tpu`` import defeats the stub loader
    outright (the package __init__ pulls model code).  The same rule
    pins the bounded-staging half of the query contract: any per-part /
    per-candidate accumulator inside a hierarchy class must be bounded
    (a ``deque(maxlen=)``, a ``len()`` cap, an eviction call, or a
    ``del buf[k:]`` trim) — an index scan that staged every part before
    ranking would make query memory proportional to the INDEX size
    instead of one bulk chunk.
"""

from __future__ import annotations

import ast
from typing import List

from glom_tpu.analysis.engine import Finding, ModuleContext, Rule, dotted_name

#: top-level import roots forbidden in the jax-free index modules
_FORBIDDEN_ROOTS = {"jax", "jaxlib", "glom_tpu"}

#: growth calls that accumulate one element per invocation
_GROWTH_METHODS = {"append", "extend", "appendleft", "add"}
#: eviction calls that count as bounding evidence for an attribute
_EVICT_METHODS = {"pop", "popleft", "popitem", "clear"}
#: constructors whose result is unbounded by default
_UNBOUNDED_CTORS = {"list", "dict", "set", "OrderedDict", "defaultdict"}


def _self_attr(node) -> str:
    """``self.X`` -> ``"X"``, else ``""``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


class HierarchyIsolationRule(Rule):
    name = "hierarchy-isolation"
    severity = "error"
    description = ("hierarchy index module imports jax or the glom_tpu "
                   "package (it must stay stub-loadable: stdlib + numpy "
                   "+ mmap only), or grows an unbounded staging buffer — "
                   "query memory is bounded by one bulk chunk, never the "
                   "index size")

    @staticmethod
    def _in_scope(relpath: str) -> bool:
        # component match, not substring (the obs-debug-in-cache
        # convention): anything under a hierarchy/ package directory
        parts = relpath.split("/")
        return "hierarchy" in parts[:-1]

    @staticmethod
    def _index_module(relpath: str) -> bool:
        # the jax-free boundary applies to the index store modules only:
        # parse.py is the traced half and imports jax on purpose
        return relpath.split("/")[-1] == "index.py"

    # -- jax-free / package-free half ------------------------------

    def _import_findings(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) and node.level > 0:
                # a relative import IS a glom_tpu package import: the
                # stub loader materializes index.py with its package
                # replaced by an empty namespace
                findings.append(ctx.finding(
                    self, node,
                    "relative import in a hierarchy index module: the "
                    "module must load with its package stubbed out "
                    "(tools/_obsload.py) — inline the helper instead"))
                continue
            mod = (node.module or "" if isinstance(node, ast.ImportFrom)
                   else "")
            dotted_all = ([mod] if mod else [a.name for a in node.names])
            for dotted in dotted_all:
                root = dotted.split(".")[0]
                if root not in _FORBIDDEN_ROOTS:
                    continue
                why = ("drags the jax runtime (and a device probe) into "
                       "every offline index audit"
                       if root in ("jax", "jaxlib") else
                       "defeats the _obsload stub loader — the package "
                       "__init__ pulls model code")
                findings.append(ctx.finding(
                    self, node,
                    f"forbidden import {dotted!r} in a hierarchy index "
                    f"module: index.py is the deviceless read side "
                    f"(stdlib + numpy + mmap only) and a {root} import "
                    f"{why}"))
        return findings

    # -- bounded-staging half (the obs-unbounded-series machinery,
    #    scoped to hierarchy classes) ------------------------------

    @staticmethod
    def _unbounded_init(value) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            d = dotted_name(value.func) or ""
            base = d.split(".")[-1]
            if base == "deque":
                return not any(kw.arg == "maxlen" for kw in value.keywords)
            return base in _UNBOUNDED_CTORS
        return False

    def _class_findings(self, ctx: ModuleContext,
                        cls: ast.ClassDef) -> List[Finding]:
        unbounded: dict = {}     # attr -> init node
        evidence: set = set()    # attrs with cap/eviction anywhere in class
        growth: List = []        # (attr, node, kind)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr and self._unbounded_init(node.value):
                        unbounded.setdefault(attr, node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                attr = _self_attr(node.target)
                if attr and self._unbounded_init(node.value):
                    unbounded.setdefault(attr, node)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            evidence.add(attr)
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "len" and node.args):
                    attr = _self_attr(node.args[0])
                    if attr:
                        evidence.add(attr)
                elif isinstance(node.func, ast.Attribute):
                    attr = _self_attr(node.func.value)
                    if attr and node.func.attr in _EVICT_METHODS:
                        evidence.add(attr)
        for method in cls.body:
            if (not isinstance(method,
                               (ast.FunctionDef, ast.AsyncFunctionDef))
                    or method.name == "__init__"):
                continue
            for node in ast.walk(method):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _GROWTH_METHODS):
                    attr = _self_attr(node.func.value)
                    if attr:
                        growth.append((attr, node, node.func.attr))
        findings: List[Finding] = []
        flagged: set = set()
        for attr, node, kind in growth:
            if attr not in unbounded or attr in evidence or attr in flagged:
                continue
            flagged.add(attr)
            findings.append(ctx.finding(
                self, node,
                f"self.{attr} stages per part/candidate ({kind}) but is "
                f"initialized unbounded and class {cls.name} never caps "
                f"or evicts it — a query over a grown index would stage "
                f"the whole index in memory; trim to k after every part "
                f"(deque(maxlen=), a len() bound, or del buf[k:])"))
        return findings

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not self._in_scope(ctx.relpath):
            return []
        findings: List[Finding] = []
        if self._index_module(ctx.relpath):
            findings.extend(self._import_findings(ctx))
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._class_findings(ctx, node))
        return findings


HIERARCHY_RULES = (HierarchyIsolationRule,)
