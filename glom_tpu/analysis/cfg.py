"""glomlint dataflow engine — intraprocedural control-flow graphs + solver.

The v1 rule packs are flow-insensitive: they walk the AST and match
shapes.  The review findings they kept missing are *path* bugs — a gate
closed but never reopened on the exception path, a staged param tree
stranded after a failed prepare, taint flowing around a loop back edge.
This module supplies the machinery those rules need:

  * :func:`build_cfg` — a statement-granularity CFG over ``ast`` for one
    function body (or a module body): branches, loops (back edges,
    ``else`` clauses, ``while True`` without a false edge), ``with``,
    ``try/except/else/finally``, and the nonlocal exits — ``return``,
    ``raise``, ``break``, ``continue``.  Two distinct exit nodes:
    ``cfg.exit`` (return / fall-off-the-end) and ``cfg.raise_exit``
    (uncaught exception), so a rule can say "the exception path misses
    the release" and mean exactly that.
  * ``finally`` landing pads — the finally body is laid down once per
    continuation kind (normal, raise, return, break, continue) so its
    semantics are exact: a ``return`` inside ``finally`` overrides the
    pending continuation, a ``raise`` inside ``finally`` abandons it —
    the "finally with return" edge case is graph structure, not a
    special case in every rule.
  * exception edges — any statement that *may raise* (contains a call,
    subscript, ``raise``, ``assert``, or ``await``; compound statements
    contribute only their header expressions) gets an edge to the
    innermost handler dispatch, or to ``raise_exit`` through every
    enclosing ``finally``.
  * :func:`solve_forward` — a worklist gen/kill solver over frozensets:
    ``may=True`` unions over paths (leak/taint analyses), ``may=False``
    intersects (must-precede / already-released analyses).

Stdlib-only (``ast``), same as the rest of the engine: no jax import, no
accelerator, identical behavior in CI / tier-1 / a laptop.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

__all__ = ["CFG", "CFGNode", "build_cfg", "solve_forward", "may_raise",
           "header_exprs"]


class CFGNode:
    """One CFG node.  ``stmt`` is the underlying AST statement (None for
    synthetic nodes); ``kind`` is 'stmt', 'handler', or a synthetic kind
    ('entry', 'exit', 'raise', 'dispatch', 'finally')."""

    __slots__ = ("stmt", "kind", "succs", "preds", "index")

    def __init__(self, stmt: Optional[ast.AST], kind: str, index: int):
        self.stmt = stmt
        self.kind = kind
        self.index = index
        self.succs: List[Tuple["CFGNode", str]] = []
        self.preds: List[Tuple["CFGNode", str]] = []

    @property
    def lineno(self) -> Optional[int]:
        return getattr(self.stmt, "lineno", None)

    def __repr__(self) -> str:  # debugging aid, not output format
        what = self.kind if self.stmt is None else ast.dump(self.stmt)[:40]
        return f"<CFGNode {self.index} {what} @{self.lineno}>"


class CFG:
    """CFG for one function (or module) body."""

    def __init__(self) -> None:
        self.nodes: List[CFGNode] = []
        self.entry = self._new(None, "entry")
        self.exit = self._new(None, "exit")
        self.raise_exit = self._new(None, "raise")

    def _new(self, stmt: Optional[ast.AST], kind: str) -> CFGNode:
        node = CFGNode(stmt, kind, len(self.nodes))
        self.nodes.append(node)
        return node

    def _edge(self, src: CFGNode, dst: CFGNode, kind: str = "next") -> None:
        src.succs.append((dst, kind))
        dst.preds.append((src, kind))

    def stmt_nodes(self) -> List[CFGNode]:
        return [n for n in self.nodes if n.stmt is not None]


# -- may-raise approximation ----------------------------------------------

_RAISING = (ast.Call, ast.Subscript, ast.Raise, ast.Assert, ast.Await)


def _walk_no_scopes(node: ast.AST):
    """ast.walk that does not descend into nested function/class/lambda
    bodies — a contained lambda's calls don't execute at this statement."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def header_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a compound statement evaluates AT its own node
    (its body statements are separate nodes): the if/while test, the for
    iterable, the with context expressions.  Simple statements evaluate
    themselves."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def may_raise(stmt: ast.stmt) -> bool:
    """Whether this node's own evaluation can raise: contains a call,
    subscript, raise, assert, or await in its header expressions.  Plain
    attribute loads/stores and name binds are treated as non-raising —
    treating *everything* as raising would make every release demand a
    ``finally`` and drown the path rules in noise."""
    for expr in header_exprs(stmt):
        for node in _walk_no_scopes(expr):
            if isinstance(node, _RAISING):
                return True
    return False


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value) is True


def _catches_everything(handlers: Sequence[ast.ExceptHandler]) -> bool:
    """True when some handler is ``except:`` / ``except BaseException`` /
    ``except Exception`` — for lint purposes the dispatch then has no
    fall-through to the outer raise path (KeyboardInterrupt pedantry
    would only add noise paths every rule has to ignore)."""
    for h in handlers:
        if h.type is None:
            return True
        names = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for n in names:
            base = n
            while isinstance(base, ast.Attribute):
                base = base.value  # builtins.Exception
            tail = n.attr if isinstance(n, ast.Attribute) else getattr(
                n, "id", None)
            if tail in ("Exception", "BaseException"):
                return True
    return False


# -- builder ---------------------------------------------------------------

_Preds = List[Tuple[CFGNode, str]]


@dataclasses.dataclass
class _Ctx:
    """Where nonlocal control transfers go from the current position.
    Each field wires an edge from the source node to the right target —
    through every enclosing ``finally`` landing pad (the wrapping happens
    in :meth:`_Builder._build_try`)."""

    raise_to: Callable[[CFGNode], None]
    return_to: Callable[[CFGNode], None]
    break_to: Optional[Callable[[CFGNode], None]] = None
    continue_to: Optional[Callable[[CFGNode], None]] = None


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # each _build_* returns the dangling (node, edge-kind) pairs that fall
    # through to whatever statement comes next

    def build_block(self, body: Sequence[ast.stmt], preds: _Preds,
                    ctx: _Ctx) -> _Preds:
        for stmt in body:
            preds = self._build_stmt(stmt, preds, ctx)
        return preds

    def _connect(self, preds: _Preds, node: CFGNode) -> None:
        for src, kind in preds:
            self.cfg._edge(src, node, kind)

    def _build_stmt(self, stmt: ast.stmt, preds: _Preds,
                    ctx: _Ctx) -> _Preds:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # a nested def is just a binding here; its body is its own CFG
            node = self.cfg._new(stmt, "stmt")
            self._connect(preds, node)
            return [(node, "next")]
        if isinstance(stmt, ast.If):
            return self._build_if(stmt, preds, ctx)
        if isinstance(stmt, (ast.While,)):
            return self._build_while(stmt, preds, ctx)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._build_for(stmt, preds, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._build_with(stmt, preds, ctx)
        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, preds, ctx)
        # simple statement
        node = self.cfg._new(stmt, "stmt")
        self._connect(preds, node)
        if isinstance(stmt, ast.Return):
            if may_raise(stmt):  # evaluating the return value can raise
                ctx.raise_to(node)
            ctx.return_to(node)
            return []
        if isinstance(stmt, ast.Raise):
            ctx.raise_to(node)
            return []
        if isinstance(stmt, ast.Break):
            if ctx.break_to is not None:
                ctx.break_to(node)
            return []
        if isinstance(stmt, ast.Continue):
            if ctx.continue_to is not None:
                ctx.continue_to(node)
            return []
        if may_raise(stmt):
            ctx.raise_to(node)
        return [(node, "next")]

    def _build_if(self, stmt: ast.If, preds: _Preds, ctx: _Ctx) -> _Preds:
        node = self.cfg._new(stmt, "stmt")
        self._connect(preds, node)
        if may_raise(stmt):
            ctx.raise_to(node)
        out = self.build_block(stmt.body, [(node, "true")], ctx)
        if stmt.orelse:
            out += self.build_block(stmt.orelse, [(node, "false")], ctx)
        else:
            out += [(node, "false")]
        return out

    def _loop_ctx(self, ctx: _Ctx, head: CFGNode,
                  breaks: _Preds) -> _Ctx:
        return dataclasses.replace(
            ctx,
            break_to=lambda n: breaks.append((n, "break")),
            continue_to=lambda n: self.cfg._edge(n, head, "continue"),
        )

    def _build_while(self, stmt: ast.While, preds: _Preds,
                     ctx: _Ctx) -> _Preds:
        head = self.cfg._new(stmt, "stmt")
        self._connect(preds, head)
        if may_raise(stmt):
            ctx.raise_to(head)
        breaks: _Preds = []
        body_out = self.build_block(stmt.body, [(head, "true")],
                                    self._loop_ctx(ctx, head, breaks))
        for n, kind in body_out:
            self.cfg._edge(n, head, "loop")
        if _is_const_true(stmt.test):
            # `while True`: no false edge — code after the loop is only
            # reachable via break, and the else clause never runs
            return breaks
        out: _Preds = []
        if stmt.orelse:
            out += self.build_block(stmt.orelse, [(head, "false")], ctx)
        else:
            out += [(head, "false")]
        return out + breaks

    def _build_for(self, stmt, preds: _Preds, ctx: _Ctx) -> _Preds:
        head = self.cfg._new(stmt, "stmt")
        self._connect(preds, head)
        if may_raise(stmt):
            ctx.raise_to(head)
        breaks: _Preds = []
        body_out = self.build_block(stmt.body, [(head, "iter")],
                                    self._loop_ctx(ctx, head, breaks))
        for n, kind in body_out:
            self.cfg._edge(n, head, "loop")
        out: _Preds = []
        if stmt.orelse:
            out += self.build_block(stmt.orelse, [(head, "exhausted")], ctx)
        else:
            out += [(head, "exhausted")]
        return out + breaks

    def _build_with(self, stmt, preds: _Preds, ctx: _Ctx) -> _Preds:
        node = self.cfg._new(stmt, "stmt")
        self._connect(preds, node)
        if may_raise(stmt):  # the context-manager construction/__enter__
            ctx.raise_to(node)
        # body exceptions propagate (conservative: __exit__ not assumed to
        # suppress); break/continue/return inside the body use ctx as-is
        return self.build_block(stmt.body, [(node, "enter")], ctx)

    # -- try/except/else/finally ------------------------------------------

    def _pad(self, finalbody: Sequence[ast.stmt], outer_ctx: _Ctx,
             kind: str, cont: Callable[[CFGNode], None]
             ) -> Callable[[CFGNode], None]:
        """A lazy ``finally`` landing pad for one continuation kind: the
        first transfer of that kind builds a dedicated copy of the
        finally body; its normal exits resume the original continuation.
        A raise/return/break/continue *inside* the finally body routes
        through ``outer_ctx`` instead — overriding the pending
        continuation, exactly Python's semantics."""
        cell: Dict[str, CFGNode] = {}

        def route(src: CFGNode) -> None:
            if "pad" not in cell:
                pad = self.cfg._new(None, "finally")
                cell["pad"] = pad
                outs = self.build_block(finalbody, [(pad, "fin")],
                                        outer_ctx)
                for n, _k in outs:
                    cont(n)
            self.cfg._edge(src, cell["pad"], kind)
        return route

    def _build_try(self, stmt: ast.Try, preds: _Preds,
                   ctx: _Ctx) -> _Preds:
        if stmt.finalbody:
            inner = _Ctx(
                raise_to=self._pad(stmt.finalbody, ctx, "exc",
                                   ctx.raise_to),
                return_to=self._pad(stmt.finalbody, ctx, "return",
                                    ctx.return_to),
                break_to=None if ctx.break_to is None else self._pad(
                    stmt.finalbody, ctx, "break", ctx.break_to),
                continue_to=None if ctx.continue_to is None else self._pad(
                    stmt.finalbody, ctx, "continue", ctx.continue_to),
            )
        else:
            inner = ctx
        out = self._build_try_core(stmt, preds, inner)
        if stmt.finalbody:
            # normal completion runs the finally too
            pad = self.cfg._new(None, "finally")
            self._connect(out, pad)
            out = self.build_block(stmt.finalbody, [(pad, "fin")], ctx)
        return out

    def _build_try_core(self, stmt: ast.Try, preds: _Preds,
                        ctx: _Ctx) -> _Preds:
        dispatch = self.cfg._new(None, "dispatch")
        body_ctx = dataclasses.replace(
            ctx, raise_to=lambda n: self.cfg._edge(n, dispatch, "exc"))
        body_out = self.build_block(stmt.body, preds, body_ctx)
        if stmt.orelse:
            # else runs after the try completed; its exceptions are NOT
            # caught by this try's handlers
            body_out = self.build_block(stmt.orelse, body_out, ctx)
        out: _Preds = list(body_out)
        for h in stmt.handlers:
            h_node = self.cfg._new(h, "handler")
            self.cfg._edge(dispatch, h_node, "match")
            out += self.build_block(h.body, [(h_node, "caught")], ctx)
        if not _catches_everything(stmt.handlers):
            # an exception no handler matches propagates out (through any
            # enclosing finally — ctx.raise_to is already wrapped)
            ctx.raise_to(dispatch)
        return out


def build_cfg(fn) -> CFG:
    """CFG for ``fn`` — a FunctionDef/AsyncFunctionDef, or a plain list of
    statements (a module body)."""
    body = fn if isinstance(fn, list) else fn.body
    cfg = CFG()
    ctx = _Ctx(
        raise_to=lambda n: cfg._edge(n, cfg.raise_exit, "exc"),
        return_to=lambda n: cfg._edge(n, cfg.exit, "return"),
    )
    out = _Builder(cfg).build_block(body, [(cfg.entry, "next")], ctx)
    for n, kind in out:
        cfg._edge(n, cfg.exit, kind)
    return cfg


# -- forward dataflow solver ----------------------------------------------

State = FrozenSet
Transfer = Callable[[CFGNode, State], State]


def solve_forward(cfg: CFG, transfer: Transfer, *, may: bool = True,
                  entry_state: State = frozenset(),
                  exc_transfer: Optional[Transfer] = None
                  ) -> Dict[CFGNode, Tuple[State, State, State]]:
    """Worklist fixpoint of a forward gen/kill analysis.

    ``transfer(node, in_state) -> out_state`` must be monotone in the
    facts it adds/removes.  ``may=True`` joins by union (a fact holds if
    it holds on SOME path in), ``may=False`` by intersection (ALL paths).

    ``exc_transfer``, when given, produces the state carried by the
    node's OWN exception edges instead of ``transfer``'s — the standard
    use: an acquire-like event must not be visible on its own
    statement's exception edge (if the acquiring call raised, the
    acquisition never happened), while a release-like event should be
    (assuming the release failed too would flag every ``finally``).

    Returns ``{node: (in_state, out_state, exc_out_state)}`` for
    reachable nodes only — unreachable code contributes no facts.
    """
    if exc_transfer is None:
        exc_transfer = transfer
    in_s: Dict[CFGNode, State] = {cfg.entry: entry_state}
    out_s: Dict[CFGNode, State] = {}
    exc_s: Dict[CFGNode, State] = {}
    work = [cfg.entry]
    on_work = {cfg.entry}

    def edge_out(pred: CFGNode, kind: str) -> State:
        return exc_s[pred] if kind == "exc" else out_s[pred]

    while work:
        node = work.pop()
        on_work.discard(node)
        out = transfer(node, in_s[node])
        exc_out = exc_transfer(node, in_s[node])
        if node in out_s and out_s[node] == out and exc_s[node] == exc_out:
            continue
        out_s[node] = out
        exc_s[node] = exc_out
        for succ, _kind in node.succs:
            pred_outs = [edge_out(p, k) for p, k in succ.preds
                         if p in out_s]
            if may:
                new_in: State = frozenset().union(*pred_outs)
            else:
                new_in = pred_outs[0]
                for s in pred_outs[1:]:
                    new_in = new_in & s
            if succ not in in_s or in_s[succ] != new_in:
                in_s[succ] = new_in
                if succ not in on_work:
                    work.append(succ)
                    on_work.add(succ)
            elif succ not in out_s:
                if succ not in on_work:
                    work.append(succ)
                    on_work.add(succ)
    return {n: (in_s[n], out_s[n], exc_s[n]) for n in cfg.nodes
            if n in out_s}


def witness_path(cfg: CFG, results: Dict[CFGNode, Tuple[State, State,
                                                        State]],
                 fact, source: CFGNode, sink: CFGNode
                 ) -> List[CFGNode]:
    """A shortest path source -> sink along which ``fact`` survives on
    every traversed edge (the leak witness a finding cites).  Empty when
    no such path exists."""
    from collections import deque

    if source not in results:
        return []
    prev: Dict[CFGNode, CFGNode] = {}
    q = deque([source])
    seen = {source}
    while q:
        cur = q.popleft()
        if cur is sink:
            path = [cur]
            while path[-1] is not source:
                path.append(prev[path[-1]])
            return list(reversed(path))
        for succ, kind in cur.succs:
            if succ in seen or cur not in results:
                continue
            carried = (results[cur][2] if kind == "exc"
                       else results[cur][1])
            if fact not in carried:
                continue
            seen.add(succ)
            prev[succ] = cur
            q.append(succ)
    return []
