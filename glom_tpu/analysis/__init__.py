"""glomlint: project-native static analysis for JAX/TPU and concurrency
hazards.  See :mod:`glom_tpu.analysis.engine` for the rule engine and
``docs/ANALYSIS.md`` for the rule catalog; ``tools/lint.py`` is the CLI
and the CI gate."""

from glom_tpu.analysis.engine import (  # noqa: F401
    AnalysisResult, Finding, ModuleContext, Rule, analyze, load_baseline,
    split_baseline, write_baseline,
)
from glom_tpu.analysis.rules_bulk import BULK_RULES
from glom_tpu.analysis.rules_concurrency import CONCURRENCY_RULES
from glom_tpu.analysis.rules_hierarchy import HIERARCHY_RULES
from glom_tpu.analysis.rules_jax import JAX_RULES
from glom_tpu.analysis.rules_obs import OBS_RULES
from glom_tpu.analysis.rules_paths import PATH_RULES
from glom_tpu.analysis.rules_races import RACE_RULES
from glom_tpu.analysis.rules_sharding import SHARDING_RULES

ALL_RULE_CLASSES = (tuple(JAX_RULES) + tuple(CONCURRENCY_RULES)
                    + tuple(OBS_RULES) + tuple(PATH_RULES)
                    + tuple(SHARDING_RULES) + tuple(RACE_RULES)
                    + tuple(BULK_RULES) + tuple(HIERARCHY_RULES))


def default_rules(names=None):
    """Fresh rule instances (rules carry per-run state for whole-program
    passes).  ``names`` filters by rule id."""
    rules = [cls() for cls in ALL_RULE_CLASSES]
    if names:
        wanted = set(names)
        unknown = wanted - {r.name for r in rules}
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: "
                f"{sorted(r.name for r in rules)}")
        rules = [r for r in rules if r.name in wanted]
    return rules


__all__ = ["AnalysisResult", "Finding", "ModuleContext", "Rule",
           "analyze", "default_rules", "load_baseline", "split_baseline",
           "write_baseline", "ALL_RULE_CLASSES"]
