"""glomlint concurrency rule pack — the threaded-serving hazard classes.

  * ``conc-lock-order`` — whole-program lock-acquisition-order graph over
    ``serving/`` + ``resilience/``: an edge A→B for every ``with
    self.B`` entered while ``self.A`` is held (including through
    same-class method calls).  A cycle is a deadlock waiting for the
    right thread interleaving; a self-edge is a re-acquisition that
    deadlocks a plain ``threading.Lock`` outright.
  * ``conc-check-then-act`` — the PR 7 commit-gate TOCTOU: an ``if`` on
    lock-guarded state taken OUTSIDE the lock, acting under the lock
    inside its body without re-checking.  The gate the check saw open can
    close before the act.
  * ``conc-raw-clock`` — ``time.time()``/``time.monotonic()`` in a module
    whose classes accept ``clock=``: every such call is invisible to the
    fake-clock tests the injectable pattern exists for (see
    ``obs/tracing.py`` for the canonical form).
  * ``conc-heartbeat-raw-clock`` — the stronger form of the clock rule
    for ``resilience/`` modules implementing heartbeat/election logic
    (``resilience/elastic.py``): raw clock reads AND real sleeps are
    errors there even without a ``clock=`` param in scope, because
    staleness/election/backoff decisions must replay under a fake clock.
  * ``conc-thread-daemon`` — ``threading.Thread`` created without
    ``daemon=`` and never joined: shutdown hangs on it, or it dies
    mid-write at interpreter teardown.
  * ``conc-broad-except`` — ``except Exception`` that neither re-raises,
    logs, nor even reads the exception: the failure class that turned
    torn checkpoints into silent serving staleness before PR 5 made every
    swallow observable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from glom_tpu.analysis.engine import (
    Finding, ModuleContext, Rule, child_blocks, dotted_name, is_compound,
    is_self_attr, terminal_name, with_lock_attrs,
)


class LockOrderRule(Rule):
    name = "conc-lock-order"
    severity = "error"
    description = ("cycle in the lock-acquisition-order graph "
                   "(serving/ + resilience/): deadlock under the right "
                   "thread interleaving")

    #: path components in scope for graph construction
    SCOPE_DIRS: Tuple[str, ...] = ("serving", "resilience")

    def __init__(self) -> None:
        # class key -> {"edges": {(a, b): (path, line)},
        #               "calls": [(caller, held tuple, callee, path, line)],
        #               "acquires": {method: {lock: (path, line)}}}
        self._classes: Dict[str, Dict] = {}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        dirs = ctx.relpath.split("/")[:-1]
        if not any(d in dirs for d in self.SCOPE_DIRS):
            return []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(node, ctx)
        return []

    def _collect_class(self, cls: ast.ClassDef, ctx: ModuleContext) -> None:
        key = f"{ctx.relpath}::{cls.name}"
        info = self._classes.setdefault(
            key, {"edges": {}, "calls": [], "acquires": {}})
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            acquires: Dict[str, Tuple[str, int]] = {}
            info["acquires"][method.name] = acquires
            self._walk(method.body, [], info, acquires, method.name, ctx)

    def _walk(self, body: Sequence[ast.stmt], held: List[str], info: Dict,
              acquires: Dict, method: str, ctx: ModuleContext) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            locks = (with_lock_attrs(stmt)
                     if isinstance(stmt, ast.With) else [])
            if locks:
                for lock in locks:
                    acquires.setdefault(lock, (ctx.relpath, stmt.lineno))
                    for h in held:
                        info["edges"].setdefault(
                            (h, lock), (ctx.relpath, stmt.lineno))
                self._record_calls(stmt, held, info, ctx, method,
                                   header_only=True)
                self._walk(stmt.body, held + locks, info, acquires, method,
                           ctx)
                continue
            # record every self-call, even lock-free ones: a caller's
            # effective acquisitions must include its callees' (the
            # multi-hop chain a->m1->m2->lock)
            self._record_calls(stmt, held, info, ctx, method,
                               header_only=is_compound(stmt))
            for block in child_blocks(stmt):
                self._walk(block, held, info, acquires, method, ctx)

    def _record_calls(self, stmt: ast.AST, held: List[str], info: Dict,
                      ctx: ModuleContext, caller: str,
                      header_only: bool) -> None:
        """``self.m()`` call sites with the lock stack held at the call
        (interprocedural edges are expanded in finalize)."""
        if header_only:
            roots = [getattr(stmt, f) for f in ("test", "iter", "subject")
                     if isinstance(getattr(stmt, f, None), ast.AST)]
            roots += [item.context_expr
                      for item in getattr(stmt, "items", []) or []]
        else:
            roots = [stmt]
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    callee = is_self_attr(node.func)
                    if callee:
                        info["calls"].append(
                            (caller, tuple(held), callee, ctx.relpath,
                             node.lineno))

    def finalize(self) -> List[Finding]:
        findings: List[Finding] = []
        for key, info in sorted(self._classes.items()):
            # interprocedural expansion, phase 1: propagate EFFECTIVE
            # acquisitions (locks a method takes itself or through any
            # chain of same-class callees) to a true fixpoint — a->m1,
            # m1->m2, m2 takes B must give a an effective B
            eff: Dict[str, Dict[str, Tuple[str, int]]] = {
                m: dict(locks) for m, locks in info["acquires"].items()}
            changed = True
            while changed:
                changed = False
                for caller, _held, callee, _path, _line in info["calls"]:
                    for lock, loc in eff.get(callee, {}).items():
                        cur = eff.setdefault(caller, {})
                        if lock not in cur:
                            cur[lock] = loc
                            changed = True
            # phase 2: while holding A, a self.m() call contributes edges
            # A -> every lock m effectively acquires
            for _caller, held, callee, path, line in info["calls"]:
                for lock in eff.get(callee, {}):
                    for h in held:
                        info["edges"].setdefault((h, lock), (path, line))

            graph: Dict[str, Set[str]] = {}
            for (a, b) in info["edges"]:
                graph.setdefault(a, set()).add(b)
            for cycle in _find_cycles(graph):
                locs = []
                for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                    loc = info["edges"].get((a, b))
                    if loc:
                        locs.append(f"{a}->{b} at {loc[0]}:{loc[1]}")
                first = cycle[1] if len(cycle) > 1 else cycle[0]
                path, line = info["edges"][(cycle[0], first)]
                order = " -> ".join(cycle + [cycle[0]])
                kind = ("re-acquired while already held (plain "
                        "threading.Lock self-deadlocks)"
                        if len(cycle) == 1 else "acquisition-order cycle")
                findings.append(Finding(
                    rule=self.name, severity=self.severity, path=path,
                    line=line, col=0,
                    message=f"{key}: lock {kind}: {order} "
                            f"({'; '.join(locs)})"))
        return findings


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles, each reported once (canonicalized rotation)."""
    cycles: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str], visiting: Set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                rot = min(range(len(path)),
                          key=lambda i: path[i:] + path[:i])
                canon = tuple(path[rot:] + path[:rot])
                if canon not in seen:
                    seen.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visiting and nxt > start:
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


class CheckThenActRule(Rule):
    name = "conc-check-then-act"
    severity = "error"
    description = ("if on lock-guarded state outside the lock, acting "
                   "under the lock inside the branch without re-checking "
                   "(PR 7 commit-gate TOCTOU)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(cls, ctx))
        return findings

    def _check_class(self, cls: ast.ClassDef, ctx: ModuleContext
                     ) -> List[Finding]:
        guarded = self._guarded_attrs(cls)
        if not guarded:
            return []
        findings: List[Finding] = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            self._walk(method.body, False, guarded, ctx, findings)
        return findings

    def _guarded_attrs(self, cls: ast.ClassDef) -> Set[str]:
        """self-attributes written inside a ``with self.<lock>:`` block
        anywhere in the class — the state the lock exists to guard."""
        guarded: Set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.With) and with_lock_attrs(node)):
                continue
            for inner in ast.walk(node):
                if (isinstance(inner, ast.Attribute)
                        and isinstance(inner.ctx, ast.Store)):
                    attr = is_self_attr(inner)
                    if attr:
                        guarded.add(attr)
        return guarded

    def _walk(self, body: Sequence[ast.stmt], under_lock: bool,
              guarded: Set[str], ctx: ModuleContext,
              findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With) and with_lock_attrs(stmt):
                self._walk(stmt.body, True, guarded, ctx, findings)
                continue
            if isinstance(stmt, ast.If) and not under_lock:
                checked = self._guarded_reads(stmt.test, guarded)
                if checked:
                    for branch in (stmt.body, stmt.orelse):
                        w = self._first_lock_with(branch)
                        if w is not None and not self._rechecks(w, checked):
                            findings.append(ctx.finding(
                                self, stmt,
                                f"check of lock-guarded "
                                f"{sorted('self.' + c for c in checked)} "
                                f"outside the lock, then acting under "
                                f"{'/'.join(with_lock_attrs(w))} at line "
                                f"{w.lineno} without re-checking: the "
                                f"state can change between check and act "
                                f"— move the check inside the lock"))
            for block in child_blocks(stmt):
                self._walk(block, under_lock, guarded, ctx, findings)

    def _guarded_reads(self, test: ast.AST, guarded: Set[str]) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(test):
            attr = is_self_attr(node) if isinstance(node, ast.Attribute) else None
            if attr and attr in guarded and isinstance(node.ctx, ast.Load):
                out.add(attr)
        return out

    def _first_lock_with(self, body: Sequence[ast.stmt]
                         ) -> Optional[ast.With]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.With) and with_lock_attrs(node):
                    return node
        return None

    def _rechecks(self, w: ast.With, checked: Set[str]) -> bool:
        """Double-checked locking is fine: the with-body re-reads the
        checked attribute in an if/while/assert test."""
        for node in ast.walk(w):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            if test is None:
                continue
            if self._guarded_reads(test, checked):
                return True
        return False


class RawClockRule(Rule):
    name = "conc-raw-clock"
    severity = "warning"
    description = ("time.time()/time.monotonic() in a module that takes "
                   "injectable clock= — invisible to fake-clock tests; "
                   "route through the injected clock (obs/tracing.py "
                   "pattern)")

    RAW_CLOCKS = {"time.time", "time.monotonic", "time.perf_counter"}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        has_clock_param = any(
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and any(a.arg == "clock" for a in (node.args.posonlyargs
                                               + node.args.args
                                               + node.args.kwonlyargs))
            for node in ast.walk(ctx.tree))
        if not has_clock_param:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and dotted_name(node.func) in self.RAW_CLOCKS):
                findings.append(ctx.finding(
                    self, node,
                    f"{dotted_name(node.func)}() in a clock-injectable "
                    f"module: fake-clock tests cannot see this timestamp "
                    f"— route it through the injected clock"))
        return findings


class ThreadLifecycleRule(Rule):
    name = "conc-thread-daemon"
    severity = "warning"
    description = ("threading.Thread without daemon= and never joined: "
                   "shutdown hangs on it or it dies mid-write at "
                   "teardown")

    THREAD_CTORS = {"threading.Thread", "Thread"}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        joined: Set[str] = set()
        named: Dict[int, Optional[str]] = {}
        aliases: Dict[str, str] = {}  # local name -> thread attr it aliases
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "join":
                t = terminal_name(node.func.value)
                if t:
                    joined.add(t)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "daemon"):
                        t = terminal_name(tgt.value)
                        if t:
                            joined.add(t)
                if (isinstance(node.value, ast.Call)
                        and dotted_name(node.value.func) in self.THREAD_CTORS
                        and len(node.targets) == 1):
                    named[id(node.value)] = terminal_name(node.targets[0])
                elif len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name):
                    # `t = self._thread` / `t = getattr(self, "_thread", ...)`
                    # — a join on the alias credits the attribute
                    src = None
                    v = node.value
                    if isinstance(v, ast.Attribute):
                        src = v.attr
                    elif (isinstance(v, ast.Call)
                            and dotted_name(v.func) == "getattr"
                            and len(v.args) >= 2
                            and isinstance(v.args[1], ast.Constant)
                            and isinstance(v.args[1].value, str)):
                        src = v.args[1].value
                    if src:
                        aliases[node.targets[0].id] = src
        for alias, attr in aliases.items():
            if alias in joined:
                joined.add(attr)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func) in self.THREAD_CTORS):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            name = named.get(id(node))
            if name is not None and name in joined:
                continue
            findings.append(ctx.finding(
                self, node,
                "Thread created without daemon= and never joined (or "
                "daemon-flagged) in this file: either pass daemon=, or "
                "join it on the shutdown path"))
        return findings


class HeartbeatRawClockRule(Rule):
    name = "conc-heartbeat-raw-clock"
    severity = "error"
    description = ("raw time.*/sleep calls in a resilience/ module that "
                   "implements heartbeat/election logic: the elastic "
                   "recovery paths must stay replayable under a fake "
                   "clock even where no clock= param is in scope")

    # conc-raw-clock only fires where a `clock=` parameter already exists —
    # the exact gap a new heartbeat helper without one slips through.  This
    # rule pins the stronger contract on the modules whose CORRECTNESS
    # depends on injected time (staleness judgments, election timing,
    # backoff arithmetic): any raw clock READ or real sleep there is an
    # error, clock= param or not.  time.sleep is included: a real sleep in
    # a heartbeat path stalls the fake-clock simulation forever.
    SCOPE_DIRS: Tuple[str, ...] = ("resilience",)
    MARKERS: Tuple[str, ...] = ("heartbeat", "elect")
    RAW_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                 "time.sleep"}

    def check(self, ctx: ModuleContext) -> List[Finding]:
        dirs = ctx.relpath.split("/")[:-1]
        if not any(d in dirs for d in self.SCOPE_DIRS):
            return []
        defines_heartbeat = any(
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
            and any(m in node.name.lower() for m in self.MARKERS)
            for node in ast.walk(ctx.tree))
        if not defines_heartbeat:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and dotted_name(node.func) in self.RAW_CALLS):
                findings.append(ctx.finding(
                    self, node,
                    f"{dotted_name(node.func)}() in a heartbeat/election "
                    f"module: staleness and election decisions must flow "
                    f"through the injected clock/sleep "
                    f"(resilience/elastic.py SimClock pattern) or "
                    f"fake-clock chaos replay breaks"))
        return findings


_LOG_CALL_NAMES = {"warn", "warning", "error", "exception", "critical",
                   "info", "debug", "log", "print", "print_exc", "write",
                   "fail", "capture"}


class BroadExceptRule(Rule):
    name = "conc-broad-except"
    severity = "warning"
    description = ("except Exception that neither re-raises, logs, nor "
                   "reads the exception: failures vanish (pre-PR 5 "
                   "silent-staleness class)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._handles(node):
                continue
            findings.append(ctx.finding(
                self, node,
                "broad `except Exception` swallows the failure: narrow "
                "the exception type, log/count it with the error "
                "attached, re-raise, or suppress with a reason"))
        return findings

    @staticmethod
    def _is_broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare except:
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        for n in names:
            if dotted_name(n) in {"Exception", "BaseException",
                                  "builtins.Exception",
                                  "builtins.BaseException"}:
                return True
        return False

    @staticmethod
    def _handles(handler: ast.ExceptHandler) -> bool:
        bound = handler.name
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                if t in _LOG_CALL_NAMES:
                    return True
            if (bound and isinstance(node, ast.Name) and node.id == bound
                    and isinstance(node.ctx, ast.Load)):
                return True
        return False


CONCURRENCY_RULES = (LockOrderRule, CheckThenActRule, RawClockRule,
                     HeartbeatRawClockRule, ThreadLifecycleRule,
                     BroadExceptRule)
