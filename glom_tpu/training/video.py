"""Video denoising-SSL training.

Reference analogue: the stateful-video recipe the reference documents but
ships no code for (`/root/reference/README.md:92-112` — pass ``levels``
back in across frames).  BASELINE.json config 5 is "consecutive frames
with carried ``levels`` state, batched on TPU".  ``models/video.py`` gives the one-graph rollout; this adds
the training objective on top: every frame of a noised clip rolls through
the scan-of-scans with carried state, each frame's final top level decodes
through ``patches_to_images``, and the loss is the mean frame-reconstruction
MSE.  Gradients flow through the carried state across frames (full BPTT
over the clip — the clip length is the scan dimension, so memory is
O(frames) activations unless ``config.remat`` is set).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import optax

from glom_tpu.config import GlomConfig, TrainConfig
from glom_tpu.models.heads import patches_to_images_apply
from glom_tpu.models.video import rollout
from glom_tpu.training.denoise import DenoiseState


def make_video_loss_fn(config: GlomConfig, train: TrainConfig, *, consensus_fn=None):
    """loss(params, frames, rng) -> (loss, recon_frames).

    ``frames``: clean clip ``(t, b, c, H, W)``; each frame is independently
    noised, rolled through with carried state, and reconstructed."""
    iters = train.iters if train.iters is not None else config.default_iters

    def loss_fn(params, frames, rng):
        noise = jax.random.normal(rng, frames.shape, frames.dtype) * train.noise_std
        _, states = rollout(
            params["glom"], frames + noise, config=config, iters=iters,
            return_states=True, consensus_fn=consensus_fn,
        )  # (t, b, n, L, d)
        tokens = states[:, :, :, train.loss_level]              # (t, b, n, d)
        t, b = tokens.shape[:2]
        recon = patches_to_images_apply(
            params["decoder"], tokens.reshape(t * b, *tokens.shape[2:]), config
        ).reshape(t, b, config.channels, config.image_size, config.image_size)
        acc_dt = jnp.promote_types(recon.dtype, jnp.float32)
        loss = jnp.mean((recon.astype(acc_dt) - frames.astype(acc_dt)) ** 2)
        return loss, recon

    return loss_fn


def make_video_train_step(
    config: GlomConfig,
    train: TrainConfig,
    tx: optax.GradientTransformation,
    *,
    consensus_fn=None,
    donate: bool = True,
):
    """Jitted ``state, frames -> state, metrics`` over clips."""
    loss_fn = make_video_loss_fn(config, train, consensus_fn=consensus_fn)

    def step_fn(state: DenoiseState, frames: jax.Array) -> Tuple[DenoiseState, dict]:
        rng, rng_noise = jax.random.split(state.rng)
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, frames, rng_noise
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            DenoiseState(params, opt_state, state.step + 1, rng),
            {"loss": loss, "grad_norm": optax.global_norm(grads)},
        )

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())
