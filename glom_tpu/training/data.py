"""Data pipelines.

The reference ships none (the README trains on ``torch.randn`` images).
Pipelines here:

  * ``synthetic`` — deterministic host-side random images; the zero-egress
    default and the bench workload.
  * ``folder`` — ``.npy``/``.npz`` image arrays from a local directory
    (e.g. a pre-exported CIFAR-10/ImageNet dump), resized by patch-aligned
    center crop/tile; no network access required.

Batches are NCHW float32 in [-1, 1] (matching the reference's standardized
``randn`` statistics).  A background-thread prefetcher overlaps host batch
prep with device compute — the host↔device pipelining role a torch
DataLoader would play.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from typing import Iterator, Optional

import numpy as np

from glom_tpu.resilience import faultinject


def synthetic_batches(
    batch_size: int, image_size: int, channels: int = 3, seed: int = 0
) -> Iterator[np.ndarray]:
    """Endless deterministic stream of standard-normal images."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.standard_normal(
            (batch_size, channels, image_size, image_size), dtype=np.float32
        )


def folder_batches(
    directory: str,
    batch_size: int,
    image_size: int,
    channels: int = 3,
    seed: int = 0,
    use_native: bool = True,
) -> Iterator[np.ndarray]:
    """Stream batches from ``.npy``/``.npz`` files holding ``(N, C, H, W)`` or
    ``(N, H, W, C)`` uint8/float arrays; normalized to zero-mean/unit-ish
    range and resized by nearest-neighbor to the model's image size.

    When the native core (``glom_tpu.native``) is available and the dataset
    is uint8-NHWC or float32-NCHW, batches are assembled per draw by the
    multithreaded C++ path from the raw resident buffer (no upfront
    whole-dataset conversion); otherwise the dataset is preprocessed once in
    NumPy.  Both paths produce bit-identical batches."""
    files = sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.endswith((".npy", ".npz"))
    )
    if not files:
        raise FileNotFoundError(f"no .npy/.npz files in {directory}")
    if len(files) == 1 and files[0].endswith(".npy"):
        # single .npy: memory-map it so ImageNet-scale dumps never load into
        # RAM — both the native gather and NumPy fancy-indexing read straight
        # through the mapping (pages fault in on demand)
        data = np.load(files[0], mmap_mode="r")
        if data.ndim != 4:
            raise ValueError(f"{files[0]} must hold a 4-D array, got {data.shape}")
    else:
        arrays = []
        for f in files:
            if f.endswith(".npz"):
                with np.load(f) as z:
                    for k in z.files:
                        arr = z[k]  # decompress once
                        if arr.ndim == 4:
                            arrays.append(arr)
            else:
                arr = np.load(f)
                if arr.ndim != 4:
                    raise ValueError(f"{f} must hold a 4-D array, got {arr.shape}")
                arrays.append(arr)
        data = np.concatenate(arrays, axis=0)

    is_nhwc = data.shape[-1] in (1, 3) and data.shape[1] not in (1, 3)
    native_ok = use_native and (
        (data.dtype == np.uint8 and is_nhwc)
        or (data.dtype == np.float32 and not is_nhwc)
    )
    got_channels = data.shape[-1] if is_nhwc else data.shape[1]
    if got_channels != channels:
        raise ValueError(f"dataset has {got_channels} channels, model expects {channels}")

    rng = np.random.default_rng(seed)
    n = data.shape[0]

    if native_ok:
        from glom_tpu import native

        # probe before any RNG draw so the fallback stream is identical
        if native.load() is None:
            native_ok = False

    if native_ok:
        from glom_tpu import native

        data = np.ascontiguousarray(data)
        while True:
            idx = rng.integers(0, n, size=batch_size)
            yield native.assemble_batch(data, idx, image_size)

    def _process(batch: np.ndarray) -> np.ndarray:
        if is_nhwc:
            batch = batch.transpose(0, 3, 1, 2)  # NHWC -> NCHW
        if batch.dtype == np.uint8:
            batch = batch.astype(np.float32) / 127.5 - 1.0
        else:
            batch = batch.astype(np.float32)
        return _resize_nchw(batch, image_size)

    if isinstance(data, np.memmap):
        # keep the mapping lazy: gather + convert per batch, never the whole set
        while True:
            idx = rng.integers(0, n, size=batch_size)
            yield _process(np.asarray(data[idx]))

    data = _process(data)  # small in-RAM datasets: preprocess once
    while True:
        idx = rng.integers(0, n, size=batch_size)
        yield data[idx]


def _resize_nchw(data: np.ndarray, size: int) -> np.ndarray:
    """Nearest-neighbor resize to size x size (no image libs in the
    zero-egress image); handles non-square inputs per axis."""
    h, w = data.shape[2], data.shape[3]
    if h != size:
        data = data[:, :, (np.arange(size) * h / size).astype(np.int64)]
    if w != size:
        data = data[:, :, :, (np.arange(size) * w / size).astype(np.int64)]
    return data


# single source of truth for the augmentation whitelist (augment_batch,
# augmented, and the --augment CLI choices all reference this)
AUGMENT_KINDS = ("none", "flip", "flip_crop")


def augment_batch(batch: np.ndarray, rng: np.random.Generator, kind: str) -> np.ndarray:
    """Host-side augmentation of an NCHW batch.

    ``"flip"``: random horizontal flip per image.
    ``"flip_crop"``: flip + random resized crop (scale 0.7-1.0, re-resized
    to the original size by nearest neighbor)."""
    if kind not in AUGMENT_KINDS:
        raise ValueError(f"unknown augmentation {kind!r}")
    if kind == "none":
        return batch
    b, c, h, w = batch.shape
    flips = rng.random(b) < 0.5
    # np.where allocates a fresh writable array, safe for in-place crops below
    out = np.where(flips[:, None, None, None], batch[:, :, :, ::-1], batch)
    if kind == "flip_crop":
        for i in range(b):
            scale = rng.uniform(0.7, 1.0)
            ch, cw = max(1, int(h * scale)), max(1, int(w * scale))
            y0 = rng.integers(0, h - ch + 1)
            x0 = rng.integers(0, w - cw + 1)
            crop = out[i, :, y0:y0 + ch, x0:x0 + cw]
            out[i] = _resize_nchw(np.ascontiguousarray(crop)[None], h)[0]
    return np.ascontiguousarray(out)


def augmented(it, kind: str, seed: int = 0):
    """Wrap a batch iterator with :func:`augment_batch` (own RNG stream).
    The kind is validated eagerly, at wrap time."""
    if kind not in AUGMENT_KINDS:
        raise ValueError(f"unknown augmentation {kind!r}")
    if kind == "none":
        return it
    rng = np.random.default_rng(seed + 0x5EED)

    def gen():
        for batch in it:
            yield augment_batch(batch, rng, kind)

    return gen()


def fault_injected(it: Iterator[np.ndarray]) -> Iterator[np.ndarray]:
    """The ``data`` injection site (:mod:`glom_tpu.resilience.faultinject`):
    wraps a batch iterator so an armed FaultPlan can delay, drop, or poison
    batches — or crash the pipeline — deterministically.  Batches are
    counted 1-based; disarmed cost is one no-op call per batch."""

    def gen():
        idx = 0
        for batch in it:
            idx += 1
            kind = faultinject.fire("data", step=idx)
            if kind == "drop_batch":
                continue
            if kind == "crash":
                raise faultinject.FaultError(
                    f"injected data-pipeline crash at batch {idx}"
                )
            if kind == "delay":
                time.sleep(faultinject.uniform("data", 0.05, 0.25))
            elif kind == "nan_batch":
                batch = np.full_like(batch, np.nan)
            yield batch

    return gen()


class Prefetcher:
    """Bounded background-thread prefetch of host batches (the data-loader
    overlap role; device transfer happens at dispatch inside jit).  Producer
    exceptions are captured and re-raised — original object, original
    traceback — on the consumer side as soon as the queue drains to them: a
    pipeline error must not masquerade as end-of-data.

    ``close()`` (also the context-manager exit) shuts the pipeline down
    deterministically: the worker is unblocked and joined, and an inner
    iterator exposing ``close()`` (generators; ``ImageFolderStream``'s
    decode pools) is closed too — nothing leaks until interpreter exit just
    because a consumer stopped early.  The shutdown drain is REPEATED until
    the worker exits (or a deadline passes): a single drain races a worker
    mid-``put`` that refills the just-emptied queue, leaving ``join`` to
    wait on a thread still parked against a full queue.  A worker exception
    the consumer never got to see (it stopped drawing before the queue
    reached the sentinel) is re-raised from ``close()`` — swallowing it
    would let a dying pipeline impersonate a clean early exit."""

    # class attribute (True on StatefulPrefetcher): the worker thread reads
    # it from its first iteration, so it must be set before __init__ runs
    _stateful = False

    def __init__(self, it: Iterator[np.ndarray], depth: int = 2):
        self._depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        self._done = object()
        self._error: Optional[BaseException] = None
        self._error_delivered = False
        self._stop = threading.Event()
        self._closed = False
        self._exhausted = False  # the _done sentinel was consumed
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            while not self._stop.is_set():
                try:
                    item = next(self._it)
                except StopIteration:
                    break
                # the inner cursor AFTER drawing this item rides the queue
                # with it: state_dict() answers for what was consumed, not
                # what the read-ahead produced
                state = self._it.state_dict() if self._stateful else None
                # bounded-wait put: a consumer that vanished (or called
                # close()) must not leave this thread blocked forever on a
                # full queue
                while not self._stop.is_set():
                    try:
                        self._q.put((item, state), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # re-raised in __next__ (or close())
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._done, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed or self._exhausted:
            # the sentinel is consumed exactly once; without this flag a
            # second iteration would block forever in _q.get() on a queue
            # the exited worker will never feed again (iterator protocol:
            # an exhausted iterator raises StopIteration repeatedly)
            raise StopIteration
        payload = self._q.get()
        if payload is self._done:
            self._exhausted = True
            err = self._error
            if err is not None and not self._error_delivered:
                # the original exception OBJECT, carrying the worker
                # thread's traceback — the consumer sees where the
                # pipeline actually died, not a generic queue poisoning
                self._error_delivered = True
                raise err
            raise StopIteration
        item, state = payload
        if state is not None:
            self._last_state = state
        return item

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def _stop_worker(self, timeout: float) -> bool:
        """Stop + join the worker, draining REPEATEDLY so a put in flight
        (the consumer exited with the queue full) always unblocks; True
        when the thread actually exited."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        while True:
            self._drain()
            self._thread.join(timeout=0.05)
            if not self._thread.is_alive():
                return True
            if time.monotonic() >= deadline:
                return False

    def close(self, timeout: float = 5.0) -> None:
        """Deterministic shutdown (idempotent): stop the worker, drain the
        queue until its bounded put unblocks, join, close the inner
        iterator — then surface a worker exception the consumer never saw.
        After close(), iteration raises StopIteration."""
        if self._closed:
            return
        self._closed = True
        if not self._stop_worker(timeout):
            # the worker is wedged inside next(self._it) (hung decode or
            # network read): closing a generator mid-execution raises
            # "generator already executing" — and from finally blocks that
            # would mask the exception the caller actually cares about.
            # Leave the daemon thread to die with the process.
            import warnings

            warnings.warn(
                f"Prefetcher.close(): worker did not stop within "
                f"{timeout}s; skipping inner-iterator close",
                stacklevel=2,
            )
            return
        close = getattr(self._it, "close", None)
        if callable(close):
            close()
        err = self._error
        if err is not None and not self._error_delivered:
            self._error_delivered = True
            if sys.exc_info()[0] is None:
                raise err
            # close() is running from a finally while another exception
            # propagates (the supervisor's restart routing depends on THAT
            # one): raising here would replace it and misclassify the
            # restart reason — surface the worker's death as a warning
            # instead of silently dropping it
            import warnings

            warnings.warn(
                f"Prefetcher worker failed after close "
                f"({type(err).__name__}: {err}); not re-raised because "
                f"another exception is already propagating",
                stacklevel=2,
            )

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StatefulPrefetcher(Prefetcher):
    """Prefetcher over a RESUMABLE stream (``state_dict``/
    ``load_state_dict``): read-ahead without cursor desync.  The worker
    snapshots the inner cursor alongside every item it enqueues, and
    ``state_dict()`` answers with the snapshot of the last item the
    CONSUMER took — so a checkpoint cut with ``depth`` batches in flight
    records exactly the consumed position, and a restart neither replays
    the in-flight batches nor skips them.

    ``load_state_dict`` is a rewind: the worker has read ahead of the
    restored cursor, so it is stopped and joined, the queue discarded,
    the inner stream re-seeded, and a fresh worker started."""

    _stateful = True

    def __init__(self, it, depth: int = 2):
        if not (hasattr(it, "state_dict") and hasattr(it, "load_state_dict")):
            raise TypeError(
                "StatefulPrefetcher needs a resumable inner stream "
                "(state_dict/load_state_dict); use Prefetcher for "
                "stateless iterators"
            )
        # the pre-iteration cursor: correct until the first item is consumed
        self._last_state = it.state_dict()
        super().__init__(it, depth)

    def state_dict(self) -> dict:
        return dict(self._last_state)

    def load_state_dict(self, state: dict) -> None:
        if self._closed:
            raise RuntimeError("cannot rewind a closed Prefetcher")
        if not self._stop_worker(5.0):
            raise RuntimeError(
                "prefetch worker did not stop for rewind; the inner "
                "stream cannot be re-seeded safely"
            )
        self._it.load_state_dict(state)
        self._last_state = self._it.state_dict()
        self._error = None
        self._error_delivered = False
        self._exhausted = False  # a rewound stream iterates again
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self._depth)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()


# -- exactly-once elastic data plane ---------------------------------------

def host_block(global_batch: int, host_index: int, host_count: int):
    """The deterministic per-host shard of one global batch: the CONTIGUOUS
    row block ``[host_index*B/H, (host_index+1)*B/H)``.  Contiguous (not
    striped) on purpose: concatenating all hosts' blocks in host order
    reconstructs the global batch in its original row order at ANY host
    count, which is what makes a shrink/grow restart bitwise-neutral to
    the loss (a striped layout would reorder rows — and float reductions —
    whenever the host count changed)."""
    if host_count < 1:
        raise ValueError(f"host_count must be >= 1, got {host_count}")
    if not 0 <= host_index < host_count:
        raise ValueError(
            f"host_index {host_index} out of range for host_count "
            f"{host_count}"
        )
    if global_batch % host_count != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by host_count "
            f"{host_count}"
        )
    k = global_batch // host_count
    return host_index * k, (host_index + 1) * k


class ElasticBatches:
    """Exactly-once resumable stream with deterministic per-host shard
    assignment, keyed on ``(seed, epoch, host_index, host_count)``.

    **Global-slot addressing.**  The stream is an infinite sequence of
    *global sample slots* ``0, 1, 2, ...``; one global step consumes
    ``batch_size`` consecutive slots and this host materializes only its
    :func:`host_block` of them.  Sample content is a pure function of the
    slot: synthetic mode derives each sample's RNG from ``(seed, slot)``;
    dataset mode maps ``slot -> (epoch=slot//N, perm_epoch[slot%N])``
    where ``perm_epoch`` is the per-epoch shuffle keyed on
    ``(seed, epoch)``.

    **Exactly-once cursor.**  The entire resume state is one integer —
    ``consumed``, the count of global slots drawn — checkpointed next to
    the params (``state_dict``/``load_state_dict``, the
    ``ImageFolderStream`` contract the trainer already persists).  Because
    the cursor is host-count-free, a restart with a DIFFERENT host count
    re-partitions trivially: every new host resumes at the same global
    position and takes its new block.  No slot is ever replayed or
    skipped.

    **Packing.**  Batches address slots, never epoch-aligned chunks, so an
    epoch tail short of a full batch is packed together with the next
    epoch's head instead of padded or dropped — zero pad waste by
    construction (``epochs_started`` tracks boundary crossings).
    """

    def __init__(
        self,
        batch_size: int,
        image_size: int = 8,
        channels: int = 3,
        seed: int = 0,
        *,
        host_index: int = 0,
        host_count: int = 1,
        dataset: Optional[np.ndarray] = None,
        perm_cache: Optional[dict] = None,
    ):
        host_block(batch_size, host_index, host_count)  # validate eagerly
        if dataset is not None:
            dataset = np.asarray(dataset)
            if dataset.ndim != 4:
                raise ValueError(
                    f"dataset must be (N, C, H, W), got {dataset.shape}"
                )
        self._global_batch = int(batch_size)
        self._image_size = int(image_size)
        self._channels = int(channels)
        self._seed = int(seed)
        self._host_index = int(host_index)
        self._host_count = int(host_count)
        self._dataset = dataset
        self._epoch_size = 0 if dataset is None else int(dataset.shape[0])
        self._consumed = 0  # GLOBAL slots drawn (all hosts', not just ours)
        # epoch -> permutation; shareable (HostShardedBatches hands one
        # dict to all its host streams so the O(N) shuffle happens once per
        # epoch, not once per host); bounded to the two epochs a batch can
        # straddle
        self._perm_cache: dict = perm_cache if perm_cache is not None else {}
        self.repartitioned = False

    # -- deterministic addressing -----------------------------------------
    def sample_index(self, slot: int):
        """Dataset row for a global slot (dataset mode), or the slot itself
        (synthetic mode) — the identity the exactly-once audits assert on."""
        if self._dataset is None:
            return int(slot)
        epoch, offset = divmod(int(slot), self._epoch_size)
        perm = self._perm_cache.get(epoch)
        if perm is None:
            rng = np.random.default_rng(
                np.random.SeedSequence([self._seed, epoch]))
            perm = self._perm_cache[epoch] = rng.permutation(self._epoch_size)
            for stale in [e for e in self._perm_cache
                          if e < epoch - 1 or e > epoch + 1]:
                del self._perm_cache[stale]
        return int(perm[offset])

    def _sample(self, slot: int) -> np.ndarray:
        if self._dataset is not None:
            return self._dataset[self.sample_index(slot)]
        rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, int(slot)]))
        return rng.standard_normal(
            (self._channels, self._image_size, self._image_size),
            dtype=np.float32,
        )

    @property
    def consumed(self) -> int:
        return self._consumed

    @property
    def epochs_started(self) -> int:
        """Epochs the stream has touched (0 before the first draw);
        dataset mode only — synthetic streams have no epochs."""
        if self._epoch_size == 0:
            return 0
        return -(-self._consumed // self._epoch_size)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        lo, hi = host_block(self._global_batch, self._host_index,
                            self._host_count)
        base = self._consumed
        batch = np.stack([self._sample(base + j) for j in range(lo, hi)])
        self._consumed += self._global_batch
        return batch

    # -- resume cursor (checkpointed via the trainer's data tree) ---------
    def state_dict(self) -> dict:
        """Flat int dict (the checkpoint data-tree convention).  Keys are
        FIXED across host counts so the restore template always matches;
        ``host_count`` is recorded for forensics and ignored on load."""
        return {
            "consumed": self._consumed,
            "global_batch": self._global_batch,
            "epoch_size": self._epoch_size,
            "seed": self._seed,
            "host_count": self._host_count,
        }

    def load_state_dict(self, state: dict) -> None:
        for key in ("seed", "global_batch", "epoch_size"):
            if key in state and int(state[key]) != getattr(self, f"_{key}"):
                raise ValueError(
                    f"checkpointed data cursor was written by a different "
                    f"stream: {key} {int(state[key])} != "
                    f"{getattr(self, f'_{key}')} — exactly-once resume is "
                    f"only defined within one (seed, dataset, batch) "
                    f"identity"
                )
        if ("host_count" in state
                and int(state["host_count"]) != self._host_count):
            # the re-partition case: the cursor is global, so adopting it
            # under a new host count IS the re-partition
            self.repartitioned = True
        self._consumed = int(state["consumed"])


class HostShardedBatches:
    """Single-process SIMULATION of the per-host elastic data plane: one
    :class:`ElasticBatches` per host, drawn in host order and concatenated
    into the global batch the real fleet's mesh would assemble.  Because
    each host's share is a contiguous block, the concatenation is
    bit-identical to a single global stream at ANY host count — the chaos
    harness and the elastic acceptance tests drive training through this.

    ``state_dict`` is the host-count-free global cursor, so a checkpoint
    cut at H hosts restores into an assembler built with H' hosts (the
    shrink/grow re-partition)."""

    def __init__(
        self,
        batch_size: int,
        image_size: int = 8,
        channels: int = 3,
        seed: int = 0,
        *,
        host_count: int = 1,
        dataset: Optional[np.ndarray] = None,
    ):
        perm_cache: dict = {}  # one per-epoch shuffle shared by all hosts
        self._streams = [
            ElasticBatches(
                batch_size, image_size, channels, seed,
                host_index=i, host_count=host_count, dataset=dataset,
                perm_cache=perm_cache,
            )
            for i in range(host_count)
        ]

    @property
    def host_count(self) -> int:
        return len(self._streams)

    @property
    def consumed(self) -> int:
        return self._streams[0].consumed

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        return np.concatenate([next(s) for s in self._streams], axis=0)

    def state_dict(self) -> dict:
        return self._streams[0].state_dict()

    def load_state_dict(self, state: dict) -> None:
        for s in self._streams:
            s.load_state_dict(state)

    def close(self) -> None:
        pass  # host-side numpy only; nothing to release


class _StatefulAugmented:
    """Augmentation wrapper that forwards the inner stream's resume cursor.
    Only the iteration position is exact across resume; the augmentation RNG
    restarts (stochastic augmentation needs no exact replay)."""

    def __init__(self, inner, kind: str, seed: int):
        self._inner = inner
        self._it = augmented(inner, kind, seed)

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, state):
        self._inner.load_state_dict(state)

    def close(self):
        close = getattr(self._inner, "close", None)
        if callable(close):
            close()


def make_batches(
    kind: str,
    batch_size: int,
    image_size: int,
    channels: int = 3,
    seed: int = 0,
    data_dir: Optional[str] = None,
    prefetch: int = 2,
    augment: str = "none",
    host_index: Optional[int] = None,
    host_count: int = 1,
) -> Iterator[np.ndarray]:
    if kind == "elastic":
        # exactly-once resumable stream (host_index=None: the whole-fleet
        # assembler the single-process elastic simulation trains on; an
        # int: that one host's shard view).  batch_size is the GLOBAL
        # batch.  No fault_injected wrap (it would break the state_dict
        # forwarding contract — elastic faults fire at the supervisor's
        # tick seam instead); prefetch rides the StatefulPrefetcher, whose
        # consumer-exact cursor keeps checkpoints honest about in-flight
        # read-ahead.
        if host_index is None:
            it = HostShardedBatches(batch_size, image_size, channels, seed,
                                    host_count=host_count)
        else:
            it = ElasticBatches(batch_size, image_size, channels, seed,
                                host_index=host_index,
                                host_count=host_count)
        if augment != "none":
            it = _StatefulAugmented(it, augment, seed)
        return StatefulPrefetcher(it, prefetch) if prefetch > 0 else it
    if kind == "synthetic":
        it = synthetic_batches(batch_size, image_size, channels, seed)
    elif kind == "folder":
        if data_dir is None:
            raise ValueError("folder data source needs data_dir")
        it = folder_batches(data_dir, batch_size, image_size, channels, seed)
    elif kind == "images":
        from glom_tpu.training.image_stream import ImageFolderStream

        if data_dir is None:
            raise ValueError("images data source needs data_dir")
        stream = ImageFolderStream(
            data_dir, batch_size, image_size, channels=channels, seed=seed,
            prefetch=max(prefetch, 1),
        )
        # internal per-file prefetch + a resumable cursor: no extra wrap
        # needed (its own read-ahead already reports a consumer-exact
        # cursor; an additional StatefulPrefetcher layer would only stack
        # queues); no fault_injected wrap either — it would break the
        # state_dict forwarding contract (arm faults on the stateless
        # sources instead)
        if augment == "none":
            return stream
        return _StatefulAugmented(stream, augment, seed)
    else:
        raise ValueError(f"unknown data source {kind!r}")
    it = fault_injected(augmented(it, augment, seed))
    return Prefetcher(it, prefetch) if prefetch > 0 else it
