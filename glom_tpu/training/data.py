"""Data pipelines.

The reference ships none (the README trains on ``torch.randn`` images).
Pipelines here:

  * ``synthetic`` — deterministic host-side random images; the zero-egress
    default and the bench workload.
  * ``folder`` — ``.npy``/``.npz`` image arrays from a local directory
    (e.g. a pre-exported CIFAR-10/ImageNet dump), resized by patch-aligned
    center crop/tile; no network access required.

Batches are NCHW float32 in [-1, 1] (matching the reference's standardized
``randn`` statistics).  A background-thread prefetcher overlaps host batch
prep with device compute — the host↔device pipelining role a torch
DataLoader would play.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np

from glom_tpu.resilience import faultinject


def synthetic_batches(
    batch_size: int, image_size: int, channels: int = 3, seed: int = 0
) -> Iterator[np.ndarray]:
    """Endless deterministic stream of standard-normal images."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.standard_normal(
            (batch_size, channels, image_size, image_size), dtype=np.float32
        )


def folder_batches(
    directory: str,
    batch_size: int,
    image_size: int,
    channels: int = 3,
    seed: int = 0,
    use_native: bool = True,
) -> Iterator[np.ndarray]:
    """Stream batches from ``.npy``/``.npz`` files holding ``(N, C, H, W)`` or
    ``(N, H, W, C)`` uint8/float arrays; normalized to zero-mean/unit-ish
    range and resized by nearest-neighbor to the model's image size.

    When the native core (``glom_tpu.native``) is available and the dataset
    is uint8-NHWC or float32-NCHW, batches are assembled per draw by the
    multithreaded C++ path from the raw resident buffer (no upfront
    whole-dataset conversion); otherwise the dataset is preprocessed once in
    NumPy.  Both paths produce bit-identical batches."""
    files = sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.endswith((".npy", ".npz"))
    )
    if not files:
        raise FileNotFoundError(f"no .npy/.npz files in {directory}")
    if len(files) == 1 and files[0].endswith(".npy"):
        # single .npy: memory-map it so ImageNet-scale dumps never load into
        # RAM — both the native gather and NumPy fancy-indexing read straight
        # through the mapping (pages fault in on demand)
        data = np.load(files[0], mmap_mode="r")
        if data.ndim != 4:
            raise ValueError(f"{files[0]} must hold a 4-D array, got {data.shape}")
    else:
        arrays = []
        for f in files:
            if f.endswith(".npz"):
                with np.load(f) as z:
                    for k in z.files:
                        arr = z[k]  # decompress once
                        if arr.ndim == 4:
                            arrays.append(arr)
            else:
                arr = np.load(f)
                if arr.ndim != 4:
                    raise ValueError(f"{f} must hold a 4-D array, got {arr.shape}")
                arrays.append(arr)
        data = np.concatenate(arrays, axis=0)

    is_nhwc = data.shape[-1] in (1, 3) and data.shape[1] not in (1, 3)
    native_ok = use_native and (
        (data.dtype == np.uint8 and is_nhwc)
        or (data.dtype == np.float32 and not is_nhwc)
    )
    got_channels = data.shape[-1] if is_nhwc else data.shape[1]
    if got_channels != channels:
        raise ValueError(f"dataset has {got_channels} channels, model expects {channels}")

    rng = np.random.default_rng(seed)
    n = data.shape[0]

    if native_ok:
        from glom_tpu import native

        # probe before any RNG draw so the fallback stream is identical
        if native.load() is None:
            native_ok = False

    if native_ok:
        from glom_tpu import native

        data = np.ascontiguousarray(data)
        while True:
            idx = rng.integers(0, n, size=batch_size)
            yield native.assemble_batch(data, idx, image_size)

    def _process(batch: np.ndarray) -> np.ndarray:
        if is_nhwc:
            batch = batch.transpose(0, 3, 1, 2)  # NHWC -> NCHW
        if batch.dtype == np.uint8:
            batch = batch.astype(np.float32) / 127.5 - 1.0
        else:
            batch = batch.astype(np.float32)
        return _resize_nchw(batch, image_size)

    if isinstance(data, np.memmap):
        # keep the mapping lazy: gather + convert per batch, never the whole set
        while True:
            idx = rng.integers(0, n, size=batch_size)
            yield _process(np.asarray(data[idx]))

    data = _process(data)  # small in-RAM datasets: preprocess once
    while True:
        idx = rng.integers(0, n, size=batch_size)
        yield data[idx]


def _resize_nchw(data: np.ndarray, size: int) -> np.ndarray:
    """Nearest-neighbor resize to size x size (no image libs in the
    zero-egress image); handles non-square inputs per axis."""
    h, w = data.shape[2], data.shape[3]
    if h != size:
        data = data[:, :, (np.arange(size) * h / size).astype(np.int64)]
    if w != size:
        data = data[:, :, :, (np.arange(size) * w / size).astype(np.int64)]
    return data


# single source of truth for the augmentation whitelist (augment_batch,
# augmented, and the --augment CLI choices all reference this)
AUGMENT_KINDS = ("none", "flip", "flip_crop")


def augment_batch(batch: np.ndarray, rng: np.random.Generator, kind: str) -> np.ndarray:
    """Host-side augmentation of an NCHW batch.

    ``"flip"``: random horizontal flip per image.
    ``"flip_crop"``: flip + random resized crop (scale 0.7-1.0, re-resized
    to the original size by nearest neighbor)."""
    if kind not in AUGMENT_KINDS:
        raise ValueError(f"unknown augmentation {kind!r}")
    if kind == "none":
        return batch
    b, c, h, w = batch.shape
    flips = rng.random(b) < 0.5
    # np.where allocates a fresh writable array, safe for in-place crops below
    out = np.where(flips[:, None, None, None], batch[:, :, :, ::-1], batch)
    if kind == "flip_crop":
        for i in range(b):
            scale = rng.uniform(0.7, 1.0)
            ch, cw = max(1, int(h * scale)), max(1, int(w * scale))
            y0 = rng.integers(0, h - ch + 1)
            x0 = rng.integers(0, w - cw + 1)
            crop = out[i, :, y0:y0 + ch, x0:x0 + cw]
            out[i] = _resize_nchw(np.ascontiguousarray(crop)[None], h)[0]
    return np.ascontiguousarray(out)


def augmented(it, kind: str, seed: int = 0):
    """Wrap a batch iterator with :func:`augment_batch` (own RNG stream).
    The kind is validated eagerly, at wrap time."""
    if kind not in AUGMENT_KINDS:
        raise ValueError(f"unknown augmentation {kind!r}")
    if kind == "none":
        return it
    rng = np.random.default_rng(seed + 0x5EED)

    def gen():
        for batch in it:
            yield augment_batch(batch, rng, kind)

    return gen()


def fault_injected(it: Iterator[np.ndarray]) -> Iterator[np.ndarray]:
    """The ``data`` injection site (:mod:`glom_tpu.resilience.faultinject`):
    wraps a batch iterator so an armed FaultPlan can delay, drop, or poison
    batches — or crash the pipeline — deterministically.  Batches are
    counted 1-based; disarmed cost is one no-op call per batch."""

    def gen():
        idx = 0
        for batch in it:
            idx += 1
            kind = faultinject.fire("data", step=idx)
            if kind == "drop_batch":
                continue
            if kind == "crash":
                raise faultinject.FaultError(
                    f"injected data-pipeline crash at batch {idx}"
                )
            if kind == "delay":
                time.sleep(faultinject.uniform("data", 0.05, 0.25))
            elif kind == "nan_batch":
                batch = np.full_like(batch, np.nan)
            yield batch

    return gen()


class Prefetcher:
    """Bounded background-thread prefetch of host batches (the data-loader
    overlap role; device transfer happens at dispatch inside jit).  Producer
    exceptions are captured and re-raised — original object, original
    traceback — on the consumer side as soon as the queue drains to them: a
    pipeline error must not masquerade as end-of-data.

    ``close()`` (also the context-manager exit) shuts the pipeline down
    deterministically: the worker is unblocked and joined, and an inner
    iterator exposing ``close()`` (generators; ``ImageFolderStream``'s
    decode pools) is closed too — nothing leaks until interpreter exit just
    because a consumer stopped early."""

    def __init__(self, it: Iterator[np.ndarray], depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        self._done = object()
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for item in self._it:
                # bounded-wait put: a consumer that vanished (or called
                # close()) must not leave this thread blocked forever on a
                # full queue
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # re-raised in __next__
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._done, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        item = self._q.get()
        if item is self._done:
            err = self._error
            if err is not None:
                # the original exception OBJECT, carrying the worker
                # thread's traceback — the consumer sees where the
                # pipeline actually died, not a generic queue poisoning
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        """Deterministic shutdown (idempotent): stop the worker, drain the
        queue so its bounded put unblocks, join, and close the inner
        iterator.  After close(), iteration raises StopIteration."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        while True:  # unblock a worker waiting on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # the worker is wedged inside next(self._it) (hung decode or
            # network read): closing a generator mid-execution raises
            # "generator already executing" — and from finally blocks that
            # would mask the exception the caller actually cares about.
            # Leave the daemon thread to die with the process.
            import warnings

            warnings.warn(
                "Prefetcher.close(): worker did not stop within 5s; "
                "skipping inner-iterator close",
                stacklevel=2,
            )
            return
        close = getattr(self._it, "close", None)
        if callable(close):
            close()

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _StatefulAugmented:
    """Augmentation wrapper that forwards the inner stream's resume cursor.
    Only the iteration position is exact across resume; the augmentation RNG
    restarts (stochastic augmentation needs no exact replay)."""

    def __init__(self, inner, kind: str, seed: int):
        self._inner = inner
        self._it = augmented(inner, kind, seed)

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, state):
        self._inner.load_state_dict(state)

    def close(self):
        close = getattr(self._inner, "close", None)
        if callable(close):
            close()


def make_batches(
    kind: str,
    batch_size: int,
    image_size: int,
    channels: int = 3,
    seed: int = 0,
    data_dir: Optional[str] = None,
    prefetch: int = 2,
    augment: str = "none",
) -> Iterator[np.ndarray]:
    if kind == "synthetic":
        it = synthetic_batches(batch_size, image_size, channels, seed)
    elif kind == "folder":
        if data_dir is None:
            raise ValueError("folder data source needs data_dir")
        it = folder_batches(data_dir, batch_size, image_size, channels, seed)
    elif kind == "images":
        from glom_tpu.training.image_stream import ImageFolderStream

        if data_dir is None:
            raise ValueError("images data source needs data_dir")
        stream = ImageFolderStream(
            data_dir, batch_size, image_size, channels=channels, seed=seed,
            prefetch=max(prefetch, 1),
        )
        # internal per-file prefetch + a resumable cursor: no Prefetcher wrap
        # (its read-ahead would desynchronize state_dict from the consumer);
        # no fault_injected wrap either — it would break the state_dict
        # forwarding contract (arm faults on the stateless sources instead)
        if augment == "none":
            return stream
        return _StatefulAugmented(stream, augment, seed)
    else:
        raise ValueError(f"unknown data source {kind!r}")
    it = fault_injected(augmented(it, augment, seed))
    return Prefetcher(it, prefetch) if prefetch > 0 else it
