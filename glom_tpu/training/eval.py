"""Evaluation utilities for denoising-SSL representations.

The reference ships no evaluation story; these are the framework-owned
standard probes for "did the SSL objective learn anything":

  * :func:`embed` — pooled level embeddings from the scan forward (the
    representation the README's island/clustering discussion points at).
  * :func:`linear_probe` — closed-form ridge classifier on frozen
    embeddings + accuracy (the standard SSL probe, deterministic, no
    iterative fitting).
  * :func:`reconstruction_psnr` — denoising fidelity of the decoder head.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from glom_tpu.config import GlomConfig
from glom_tpu.models import glom as glom_model
from glom_tpu.models.heads import decoder_apply


def embed_levels(
    params: dict,
    imgs: jax.Array,
    *,
    config: GlomConfig,
    iters: Optional[int] = None,
    consensus_fn=None,
    ff_fn=None,
) -> jax.Array:
    """``(b, c, H, W) -> (b, L, d)`` mean-pooled (over columns) final-state
    embeddings at EVERY level — one forward serves both the single-level
    probe and the all-levels concat probe."""
    out = glom_model.apply(
        params, imgs, config=config, iters=iters, consensus_fn=consensus_fn,
        ff_fn=ff_fn,
    )
    return jnp.mean(out, axis=1)


def embed(
    params: dict,
    imgs: jax.Array,
    *,
    config: GlomConfig,
    iters: Optional[int] = None,
    level: int = -1,
    consensus_fn=None,
    ff_fn=None,
) -> jax.Array:
    """``(b, c, H, W) -> (b, d)`` mean-pooled final-state embeddings at
    ``level``."""
    return embed_levels(
        params, imgs, config=config, iters=iters, consensus_fn=consensus_fn,
        ff_fn=ff_fn,
    )[:, level]


def linear_probe(
    train_x: jax.Array,
    train_y: jax.Array,
    test_x: jax.Array,
    test_y: jax.Array,
    *,
    num_classes: int,
    l2: float = 1e-3,
    l2_grid=None,
) -> Tuple[float, float]:
    """Closed-form ridge regression to one-hot targets on frozen embeddings;
    returns ``(train_accuracy, test_accuracy)``.

    ``l2_grid``: optional candidate list — the ridge strength is then chosen
    on a held-out tail (last 20%) of the TRAIN half and the winner refit on
    the full train half.  A fixed ``l2`` tuned for d=128 features
    over-shrinks a d=512 concat probe; the grid makes feature sets of
    different widths comparable.  Test data never influences the choice."""
    x = train_x.astype(jnp.float32)
    mean, std = x.mean(0), x.std(0) + 1e-6
    x = (x - mean) / std
    xt = (test_x.astype(jnp.float32) - mean) / std

    onehot = jax.nn.one_hot(train_y, num_classes)
    d = x.shape[1]
    eye = jnp.eye(d)

    def fit(feats, targets, reg):
        return jnp.linalg.solve(
            feats.T @ feats + reg * eye, feats.T @ targets
        )

    def acc_w(w, feats, labels):
        pred = jnp.argmax(feats @ w, axis=-1)
        return float(jnp.mean((pred == labels).astype(jnp.float32)))

    n_fit = max(1, int(len(x) * 0.8))
    # Grid selection needs a non-degenerate validation tail: below ~5
    # examples the choice is effectively random — fall back to the fixed l2.
    # len() guard: [] must fall back to the fixed l2 (best would stay None),
    # and numpy-array grids must not hit ambiguous bool(array)
    if l2_grid is not None and len(l2_grid) > 0 and len(x) - n_fit >= 5:
        # Gram/crossterm are candidate-independent; build once, solve per l2
        g = x[:n_fit].T @ x[:n_fit]
        b = x[:n_fit].T @ onehot[:n_fit]
        best = None
        for cand in l2_grid:
            w_val = jnp.linalg.solve(g + cand * eye, b)
            val_acc = acc_w(w_val, x[n_fit:], train_y[n_fit:])
            if best is None or val_acc > best[0]:
                best = (val_acc, cand)
        l2 = best[1]

    w = fit(x, onehot, l2)
    return acc_w(w, x, train_y), acc_w(w, xt, test_y)


def make_psnr_fn(
    config: GlomConfig,
    *,
    noise_std: float = 1.0,
    iters: Optional[int] = None,
    timestep: Optional[int] = None,
    level: int = -1,
    data_range: float = 2.0,
    consensus_fn=None,
    ff_fn=None,
    fused_fn=None,
    state_sharding=None,
    decoder: str = "linear",
):
    """Build the pure, jittable eval twin of the denoising objective:
    ``(params, imgs, rng) -> psnr_db`` scalar.  ``consensus_fn`` threads the
    mesh-bound ring/ulysses consensus exactly as the train step does;
    ``state_sharding`` likewise pins the scan carry (see glom.apply)."""
    if iters is None:
        iters = config.default_iters
    if timestep is None:
        timestep = iters // 2 + 1

    def psnr_fn(params: dict, imgs: jax.Array, rng: jax.Array) -> jax.Array:
        noised = imgs + jax.random.normal(rng, imgs.shape, imgs.dtype) * noise_std
        _, captured = glom_model.apply(
            params["glom"], noised, config=config, iters=iters,
            capture_timestep=timestep, consensus_fn=consensus_fn, ff_fn=ff_fn,
            fused_fn=fused_fn, state_sharding=state_sharding,
        )
        recon = decoder_apply(
            params["decoder"], captured, config, arch=decoder, level=level
        )
        mse = jnp.mean((recon.astype(jnp.float32) - imgs.astype(jnp.float32)) ** 2)
        return 20.0 * jnp.log10(data_range) - 10.0 * jnp.log10(mse)

    return psnr_fn


class EvalSuite:
    """Held-out evaluation bundle for the Trainer (VERDICT r1 item 6).

    Wraps a FIXED set of images (never seen by the train step) and runs, at
    each eval point:

      * denoising PSNR on the held-out images (same objective as training,
        fresh noise per call from the caller's rng), and
      * a linear probe on frozen pooled embeddings when labels are given:
        ridge-fit on the probe-train half, accuracy reported on the
        probe-test half — the standard "did SSL learn anything" measure
        (the reference's island/clustering discussion,
        `/root/reference/README.md:34-36`, is the motivation).

    Forward functions are jitted once; embeddings run in fixed-size chunks
    so arbitrarily large eval sets never blow device memory or recompile.
    """

    def __init__(
        self,
        config: GlomConfig,
        psnr_images,
        *,
        probe_images=None,
        probe_labels=None,
        num_classes: Optional[int] = None,
        probe_train_fraction: float = 0.5,
        probe_l2_grid=None,
        noise_std: float = 1.0,
        iters: Optional[int] = None,
        timestep: Optional[int] = None,
        level: int = -1,
        chunk: int = 32,
        consensus_fn=None,
        ff_fn=None,
        decoder: str = "linear",
    ):
        import numpy as np

        self.config = config
        self.psnr_images = np.asarray(psnr_images, np.float32)
        self.chunk = min(chunk, len(self.psnr_images))
        self._psnr = jax.jit(make_psnr_fn(
            config, noise_std=noise_std, iters=iters, timestep=timestep,
            level=level, consensus_fn=consensus_fn, ff_fn=ff_fn,
            decoder=decoder,
        ))
        self._level = level
        self._embed = jax.jit(functools.partial(
            embed_levels, config=config, iters=iters,
            consensus_fn=consensus_fn, ff_fn=ff_fn,
        ))

        self.probe_images = None
        if probe_images is not None:
            if probe_labels is None:
                raise ValueError("probe_images needs probe_labels")
            imgs = np.asarray(probe_images, np.float32)
            labels = np.asarray(probe_labels)
            if num_classes is None:
                num_classes = int(labels.max()) + 1
            # ImageFolder eval sets arrive class-grouped (sorted paths): a
            # first-k/rest split would put disjoint classes in the two
            # halves.  Shuffle deterministically so both halves mix classes.
            perm = np.random.default_rng(0xB0BE).permutation(len(imgs))
            imgs, labels = imgs[perm], labels[perm]
            n_train = max(1, int(len(imgs) * probe_train_fraction))
            self.probe_images = imgs
            self.probe_labels = labels
            self._probe_split = n_train
            self._probe_l2_grid = probe_l2_grid
            self.num_classes = num_classes

    def _chunked_embed(self, params, imgs):
        import numpy as np

        outs = []
        chunk = min(self.chunk, len(imgs))  # probe set may be < PSNR chunk
        n = (len(imgs) // chunk) * chunk
        for i in range(0, n, chunk):
            outs.append(np.asarray(self._embed(params, imgs[i:i + chunk])))
        return np.concatenate(outs), n

    def run(self, params: dict, rng: jax.Array) -> dict:
        """``{"eval_psnr_db": ..., ("probe_train_acc", "probe_test_acc",
        "probe_all_train_acc", "probe_all_test_acc")}`` — the ``probe_all``
        pair is the all-levels-concat probe; all metrics are computed on
        data the train step has never consumed."""
        import numpy as np

        psnrs = []
        n = (len(self.psnr_images) // self.chunk) * self.chunk
        for i in range(0, n, self.chunk):
            key = jax.random.fold_in(rng, i)
            psnrs.append(float(self._psnr(params, self.psnr_images[i:i + self.chunk], key)))
        metrics = {"eval_psnr_db": float(np.mean(psnrs))}

        if self.probe_images is not None:
            # (N, L, d) per-level pooled embeddings from ONE forward pass
            lvl_feats, n_used = self._chunked_embed(
                params["glom"], self.probe_images
            )
            labels = self.probe_labels[:n_used]
            k = min(self._probe_split, n_used - 1)

            def probe(feats):
                return linear_probe(
                    jnp.asarray(feats[:k]), jnp.asarray(labels[:k]),
                    jnp.asarray(feats[k:]), jnp.asarray(labels[k:]),
                    num_classes=self.num_classes,
                    l2_grid=self._probe_l2_grid,
                )

            # metric of record: the configured single level (top by default)
            tr_acc, te_acc = probe(lvl_feats[:, self._level])
            metrics["probe_train_acc"] = tr_acc
            metrics["probe_test_acc"] = te_acc
            # companion: all levels concatenated (L*d features) — the whole
            # part-whole hierarchy's linear decodability, not just the top
            all_feats = lvl_feats.reshape(len(lvl_feats), -1)
            tr_all, te_all = probe(all_feats)
            metrics["probe_all_train_acc"] = tr_all
            metrics["probe_all_test_acc"] = te_all
        return metrics


def holdout_split(files, fraction: float, *, seed: int = 0):
    """Deterministic (train_files, eval_files) split of a file list —
    eval files never enter the training stream."""
    import numpy as np

    files = list(files)
    n_eval = max(1, int(len(files) * fraction))
    perm = np.random.default_rng((seed, 0xE7A1)).permutation(len(files))
    eval_idx = set(perm[:n_eval].tolist())
    train = [f for i, f in enumerate(files) if i not in eval_idx]
    evals = [f for i, f in enumerate(files) if i in eval_idx]
    return train, evals


def reconstruction_psnr(
    params: dict,
    imgs: jax.Array,
    rng: jax.Array,
    *,
    config: GlomConfig,
    noise_std: float = 1.0,
    iters: Optional[int] = None,
    timestep: Optional[int] = None,
    level: int = -1,
    data_range: float = 2.0,
    consensus_fn=None,
) -> float:
    """One-shot convenience over :func:`make_psnr_fn` (PSNR in dB as a
    Python float); loops should build+jit the fn once instead."""
    fn = make_psnr_fn(
        config, noise_std=noise_std, iters=iters, timestep=timestep,
        level=level, data_range=data_range, consensus_fn=consensus_fn,
    )
    return float(fn(params, imgs, rng))
