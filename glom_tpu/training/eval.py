"""Evaluation utilities for denoising-SSL representations.

The reference ships no evaluation story; these are the framework-owned
standard probes for "did the SSL objective learn anything":

  * :func:`embed` — pooled level embeddings from the scan forward (the
    representation the README's island/clustering discussion points at).
  * :func:`linear_probe` — closed-form ridge classifier on frozen
    embeddings + accuracy (the standard SSL probe, deterministic, no
    iterative fitting).
  * :func:`reconstruction_psnr` — denoising fidelity of the decoder head.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from glom_tpu.config import GlomConfig
from glom_tpu.models import glom as glom_model
from glom_tpu.models.heads import patches_to_images_apply


def embed(
    params: dict,
    imgs: jax.Array,
    *,
    config: GlomConfig,
    iters: Optional[int] = None,
    level: int = -1,
    consensus_fn=None,
) -> jax.Array:
    """``(b, c, H, W) -> (b, d)`` mean-pooled final-state embeddings at
    ``level``."""
    out = glom_model.apply(
        params, imgs, config=config, iters=iters, consensus_fn=consensus_fn
    )
    return jnp.mean(out[:, :, level], axis=1)


def linear_probe(
    train_x: jax.Array,
    train_y: jax.Array,
    test_x: jax.Array,
    test_y: jax.Array,
    *,
    num_classes: int,
    l2: float = 1e-3,
) -> Tuple[float, float]:
    """Closed-form ridge regression to one-hot targets on frozen embeddings;
    returns ``(train_accuracy, test_accuracy)``."""
    x = train_x.astype(jnp.float32)
    mean, std = x.mean(0), x.std(0) + 1e-6
    x = (x - mean) / std
    xt = (test_x.astype(jnp.float32) - mean) / std

    onehot = jax.nn.one_hot(train_y, num_classes)
    d = x.shape[1]
    w = jnp.linalg.solve(x.T @ x + l2 * jnp.eye(d), x.T @ onehot)

    def acc(feats, labels):
        pred = jnp.argmax(feats @ w, axis=-1)
        return float(jnp.mean((pred == labels).astype(jnp.float32)))

    return acc(x, train_y), acc(xt, test_y)


def make_psnr_fn(
    config: GlomConfig,
    *,
    noise_std: float = 1.0,
    iters: Optional[int] = None,
    timestep: Optional[int] = None,
    level: int = -1,
    data_range: float = 2.0,
    consensus_fn=None,
    ff_fn=None,
):
    """Build the pure, jittable eval twin of the denoising objective:
    ``(params, imgs, rng) -> psnr_db`` scalar.  ``consensus_fn`` threads the
    mesh-bound ring/ulysses consensus exactly as the train step does."""
    if iters is None:
        iters = config.default_iters
    if timestep is None:
        timestep = iters // 2 + 1

    def psnr_fn(params: dict, imgs: jax.Array, rng: jax.Array) -> jax.Array:
        noised = imgs + jax.random.normal(rng, imgs.shape, imgs.dtype) * noise_std
        all_levels = glom_model.apply(
            params["glom"], noised, config=config, iters=iters, return_all=True,
            consensus_fn=consensus_fn, ff_fn=ff_fn,
        )
        recon = patches_to_images_apply(
            params["decoder"], all_levels[timestep, :, :, level], config
        )
        mse = jnp.mean((recon.astype(jnp.float32) - imgs.astype(jnp.float32)) ** 2)
        return 20.0 * jnp.log10(data_range) - 10.0 * jnp.log10(mse)

    return psnr_fn


def reconstruction_psnr(
    params: dict,
    imgs: jax.Array,
    rng: jax.Array,
    *,
    config: GlomConfig,
    noise_std: float = 1.0,
    iters: Optional[int] = None,
    timestep: Optional[int] = None,
    level: int = -1,
    data_range: float = 2.0,
    consensus_fn=None,
) -> float:
    """One-shot convenience over :func:`make_psnr_fn` (PSNR in dB as a
    Python float); loops should build+jit the fn once instead."""
    fn = make_psnr_fn(
        config, noise_std=noise_std, iters=iters, timestep=timestep,
        level=level, data_range=data_range, consensus_fn=consensus_fn,
    )
    return float(fn(params, imgs, rng))
