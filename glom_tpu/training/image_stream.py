"""ImageNet-scale streaming loader: a folder tree of JPEG/PNG files.

The reference trains on ``torch.randn`` images and ships no data pipeline at
all; this is the framework-owned loader for real image datasets (SURVEY.md
§7 step 3, BASELINE config 3).  Design:

  * **Sharded reads** — each process sees ``files[process_index::count]``;
    no coordination, no overlap, works for any process count.
  * **Deterministic + exactly resumable** — iteration order is a pure
    function of ``(seed, epoch)`` (one permutation per epoch) and a position
    cursor.  ``state_dict()`` is two integers; restoring them resumes the
    stream on the exact next batch, including across process restarts.  The
    cursor snapshots account for in-flight prefetched batches, so what you
    checkpoint is the next batch the *consumer* would have seen, not the
    producer's read-ahead.
  * **Overlapped decode** — a thread pool decodes each batch's files in
    parallel (cv2 if present, else PIL; both release the GIL in the codec)
    and ``prefetch`` whole batches are kept in flight ahead of the consumer,
    so host decode overlaps device compute without a separate DataLoader
    process tree.
  * **Static shapes** — shorter-side resize + center crop to
    ``image_size``²; partial trailing batches are dropped (epoch boundary),
    keeping every batch ``(B, C, S, S)`` so jit never recompiles.

Batches are NCHW float32 in [-1, 1], matching the rest of the pipeline
(``glom_tpu.training.data``).
"""

from __future__ import annotations

import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def list_image_files(root: str) -> list:
    """Recursive, sorted scan — the sort makes the file index stable across
    processes and restarts (the shard + shuffle math depends on it)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for f in sorted(filenames):
            if f.lower().endswith(IMAGE_EXTENSIONS):
                out.append(os.path.join(dirpath, f))
    return out


def _decode(path: str, image_size: int, channels: int) -> np.ndarray:
    """Decode + shorter-side resize + center crop -> (C, S, S) float32 in
    [-1, 1]."""
    try:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_COLOR)  # BGR uint8, HWC
        if img is None:
            raise ValueError(f"undecodable image: {path}")
        h, w = img.shape[:2]
        scale = image_size / min(h, w)
        if scale != 1.0:
            img = cv2.resize(
                img, (max(image_size, round(w * scale)),
                      max(image_size, round(h * scale))),
                interpolation=cv2.INTER_AREA if scale < 1.0 else cv2.INTER_LINEAR,
            )
        img = img[:, :, ::-1]  # BGR -> RGB
    except ImportError:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB")
            w, h = im.size
            scale = image_size / min(h, w)
            if scale != 1.0:
                im = im.resize(
                    (max(image_size, round(w * scale)), max(image_size, round(h * scale)))
                )
            img = np.asarray(im)
    h, w = img.shape[:2]
    y0, x0 = (h - image_size) // 2, (w - image_size) // 2
    img = img[y0:y0 + image_size, x0:x0 + image_size]
    arr = np.ascontiguousarray(img.transpose(2, 0, 1), dtype=np.float32)
    arr = arr / 127.5 - 1.0
    if channels != 3:
        raise ValueError(f"image stream decodes RGB (3 channels), model wants {channels}")
    return arr


def labels_from_paths(files) -> "tuple[np.ndarray, list]":
    """Class labels from the standard ImageFolder layout (label = immediate
    parent directory name).  Returns ``(labels int64, class_names)``."""
    parents = [os.path.basename(os.path.dirname(f)) for f in files]
    names = sorted(set(parents))
    index = {n: i for i, n in enumerate(names)}
    return np.asarray([index[p] for p in parents], np.int64), names


def load_images(files, image_size: int, *, channels: int = 3, workers: int = 8) -> np.ndarray:
    """Decode a fixed file list into one ``(N, C, S, S)`` float32 array
    (eval sets — bounded, held in host RAM)."""
    with ThreadPoolExecutor(max_workers=workers) as pool:
        parts = list(pool.map(lambda p: _decode(p, image_size, channels), files))
    return np.stack(parts)


class ImageFolderStream:
    """Endless batch iterator over a folder tree of images.

    ``state_dict()``/``load_state_dict()`` capture/restore the iteration
    cursor; the Trainer checkpoints them alongside the training state so a
    resumed run continues mid-epoch on the exact next batch.
    """

    def __init__(
        self,
        root: str,
        batch_size: int,
        image_size: int,
        *,
        channels: int = 3,
        seed: int = 0,
        shuffle: bool = True,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        workers: int = 8,
        prefetch: int = 4,
        files: Optional[Sequence[str]] = None,
        native_decode: Optional[bool] = None,
    ):
        """``native_decode``: decode whole batches in the C++ core (libjpeg,
        its own thread pool, zero Python per image — scales with cores where
        the per-file Python path saturates on dispatch overhead).  Default
        auto: used when the native core is jpeg-linked, the model wants RGB,
        and every file is a .jpg/.jpeg; pass False to force the Python
        decoders (cv2/PIL)."""
        if process_index is None or process_count is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        all_files = list(files) if files is not None else list_image_files(root)
        if not all_files:
            raise FileNotFoundError(f"no image files under {root}")
        self.files = all_files[process_index::process_count]
        if len(self.files) < batch_size:
            raise ValueError(
                f"process shard has {len(self.files)} images < batch_size "
                f"{batch_size} (dataset {len(all_files)} files over "
                f"{process_count} processes)"
            )
        self.batch_size = batch_size
        self.image_size = image_size
        self.channels = channels
        self.seed = seed
        self.shuffle = shuffle
        self._epoch = 0
        self._pos = 0
        self._perm = self._epoch_perm(0)
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._workers = workers
        self._prefetch = max(1, prefetch)
        self._pending: deque = deque()  # (state_before, batch result getter)
        if native_decode is None or native_decode:
            candidate = (
                channels == 3
                and all(f.lower().endswith((".jpg", ".jpeg")) for f in self.files)
            )
            if native_decode:
                from glom_tpu import native

                if not (candidate and native.has_jpeg()):
                    raise ValueError(
                        "native_decode=True but the native jpeg path is unusable "
                        "(needs channels=3, all-.jpg/.jpeg files, and a "
                        "libjpeg-linked native core); pass native_decode=None "
                        "for auto-fallback or False for the python decoders"
                    )
                native_decode = True
            else:
                # auto: defer the has_jpeg() probe to the first batch — its
                # first call may pay the one-time native build (two g++
                # attempts, up to 120s each), which must not land in the
                # constructor of users who never pull a batch
                native_decode = None if candidate else False
        # True | False | None = auto-undecided until the first __next__
        self._native_decode = native_decode
        if self._native_decode:
            self._native_pool = self._make_native_pool()

    @staticmethod
    def _make_native_pool() -> ThreadPoolExecutor:
        # ONE native batch call in flight at a time: the C++ core
        # parallelizes internally (capped at `workers` threads), so a
        # wider slot count would multiply thread usage, not throughput
        return ThreadPoolExecutor(max_workers=1)

    # -- determinism / resume --------------------------------------------
    def _epoch_perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.files))
        return np.random.default_rng((self.seed, epoch)).permutation(len(self.files))

    def state_dict(self) -> dict:
        """Cursor of the next batch the CONSUMER will receive (in-flight
        prefetched batches belong to the future, so the first pending
        entry's pre-state is the resume point)."""
        if self._pending:
            epoch, pos = self._pending[0][0]
        else:
            epoch, pos = self._epoch, self._pos
        return {"epoch": epoch, "pos": pos}

    def load_state_dict(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._pos = int(state["pos"])
        self._perm = self._epoch_perm(self._epoch)
        self._pending.clear()  # drop read-ahead from the pre-restore cursor

    # -- iteration --------------------------------------------------------
    def _advance(self):
        """Claim the next batch's paths at the producer cursor."""
        if self._pos + self.batch_size > len(self.files):
            self._epoch += 1
            self._pos = 0
            self._perm = self._epoch_perm(self._epoch)
        state = (self._epoch, self._pos)
        idx = self._perm[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        return state, [self.files[i] for i in idx]

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        if self._native_decode is None:
            # deferred auto-probe (see constructor): resolve once, here
            from glom_tpu import native

            self._native_decode = native.has_jpeg()
            if self._native_decode:
                self._native_pool = self._make_native_pool()
        while len(self._pending) < self._prefetch:
            state, paths = self._advance()
            if self._native_decode:
                # one future per batch on the single-slot native pool: the
                # C++ core runs its own (worker-capped) threads per call
                from glom_tpu import native

                fut = self._native_pool.submit(
                    native.decode_jpeg_batch, paths, self.image_size,
                    self._workers,
                )
                get = fut.result
            else:
                # per-file futures (not a nested batch task): a batch-level
                # task blocking on decodes in the same pool could deadlock it
                futs = [
                    self._pool.submit(_decode, p, self.image_size, self.channels)
                    for p in paths
                ]

                def get(futs=futs):
                    return np.stack([f.result() for f in futs])

            self._pending.append((state, get))
        _, get = self._pending.popleft()
        return get()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Deterministic shutdown of the decode pools (idempotent): drops
        read-ahead work and joins the Python pool and the native-dispatch
        slot — the ``Prefetcher.close()`` contract, so a wrapped stream
        tears down end to end instead of leaking executors."""
        self._pending.clear()
        self._pool.shutdown(wait=True, cancel_futures=True)
        pool = getattr(self, "_native_pool", None)
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "ImageFolderStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
