"""CLI embedding extraction: ``python -m glom_tpu.training.extract``.

The reference's suggested downstream use of GLOM is to read the level
states after the update loop settles ("return_all ... you can also pass
back the levels" — `/root/reference/README.md:38-53`); this turns that into
a batch workflow: load a Trainer checkpoint (self-describing via its
``config.json``), stream an ImageFolder through the forward pass, and write
mean-pooled per-column embeddings (plus labels from the directory layout)
to one ``.npz`` — ready for probes, retrieval, or clustering.

  python -m glom_tpu.training.extract --checkpoint-dir /ckpt \\
      --data-dir /data --out embeddings.npz [--level -1 | --all-levels]
"""

from __future__ import annotations

import argparse
import json
import os


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="GLOM embedding extraction")
    p.add_argument("--checkpoint-dir", required=True,
                   help="Trainer checkpoint dir (reads its config.json)")
    p.add_argument("--data-dir", required=True, help="ImageFolder root")
    p.add_argument("--out", default="embeddings.npz")
    p.add_argument("--level", type=int, default=-1,
                   help="which level's columns to mean-pool (default: top)")
    p.add_argument("--all-levels", action="store_true",
                   help="save (N, levels, dim) — one pooled vector per level")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--max-images", type=int, default=0, help="0 = all")
    p.add_argument("--platform", default="auto",
                   help="force a JAX platform (e.g. 'cpu')")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp
    import numpy as np

    from glom_tpu.models import glom as glom_model
    from glom_tpu.training.denoise import load_checkpoint_params
    from glom_tpu.training.image_stream import (
        labels_from_paths, list_image_files, load_images,
    )

    step, config, params = load_checkpoint_params(args.checkpoint_dir)

    files = list_image_files(args.data_dir)
    if args.max_images:
        files = files[:args.max_images]
    if not files:
        raise SystemExit(f"no images found under {args.data_dir}")
    labels, class_names = labels_from_paths(files)

    @jax.jit
    def forward(prm, imgs):
        out = glom_model.apply(prm, imgs, config=config, iters=args.iters)
        pooled = jnp.mean(out, axis=1)               # (b, levels, dim)
        return pooled if args.all_levels else pooled[:, args.level]

    bs = args.batch_size

    def decode(batch_files):
        imgs = load_images(batch_files, config.image_size)
        # static batch shape for the jit cache: pad the tail chunk, then trim
        pad = bs - len(batch_files)
        if pad:
            imgs = np.concatenate(
                [imgs, np.zeros((pad,) + imgs.shape[1:], imgs.dtype)]
            )
        return imgs

    # one worker thread decodes batch i+1 while the device runs batch i —
    # the decode/compute overlap ImageFolderStream gives training
    from concurrent.futures import ThreadPoolExecutor

    batches = [files[i:i + bs] for i in range(0, len(files), bs)]
    chunks = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        pending = pool.submit(decode, batches[0])
        for j, batch_files in enumerate(batches):
            imgs = pending.result()
            if j + 1 < len(batches):
                pending = pool.submit(decode, batches[j + 1])
            out = np.asarray(forward(params, imgs))
            chunks.append(out[:len(batch_files)])
    embeddings = np.concatenate(chunks)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    np.savez(
        args.out,
        embeddings=embeddings.astype(np.float32),
        labels=labels,
        class_names=np.array(class_names),
        paths=np.array(files),
        checkpoint_step=step,
        level=args.level if not args.all_levels else -999,
    )
    print(json.dumps({
        "out": args.out, "n": int(embeddings.shape[0]),
        "shape": list(embeddings.shape), "classes": len(class_names),
        "checkpoint_step": int(step),
    }))


if __name__ == "__main__":
    main()
