"""Denoising self-supervised objective and train step.

Reference analogue: the README recipe (`README.md:56-90`) — noise the image,
run the model with ``return_all=True``, decode the top level at a chosen
timestep through ``patches_to_images``, MSE against the clean image,
backprop.  The reference reads ``all_levels[7, :, :, -1]`` for iters=12
(`README.md:83`); we default the timestep to ``iters // 2 + 1`` and make
both timestep and level configurable.

TPU-native: the whole step — noise, scan forward, decode, loss, grad, optax
update — is one jitted graph.  Under a mesh, params/batch carry shardings
and XLA emits the grad psum over ICI; there is no separate DDP wrapper.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from glom_tpu.config import GlomConfig, TrainConfig
from glom_tpu.models import glom as glom_model
from glom_tpu.models.heads import decoder_apply, decoder_init


class DenoiseState(NamedTuple):
    """Carried training state: model+head params, optimizer state, step, rng."""

    params: Any          # {"glom": ..., "decoder": ...}
    opt_state: Any
    step: jax.Array
    rng: jax.Array


def init_state(
    rng: jax.Array, config: GlomConfig, tx: optax.GradientTransformation,
    *, decoder: str = "linear", decoder_hidden_mult: int = 2,
) -> DenoiseState:
    """``decoder``/``decoder_hidden_mult`` mirror the TrainConfig fields;
    the 'linear' default is the reference head (README.md:78-84)."""
    k_glom, k_dec, k_train = jax.random.split(rng, 3)
    params = {
        "glom": glom_model.init(k_glom, config),
        "decoder": decoder_init(
            k_dec, config, arch=decoder, hidden_mult=decoder_hidden_mult,
            dtype=config.param_dtype,
        ),
    }
    return DenoiseState(params, tx.init(params), jnp.zeros((), jnp.int32), k_train)


def resolve_loss_timestep(train: TrainConfig, iters: int) -> int:
    """The iteration whose state feeds the loss: ``train.loss_timestep`` when
    set (0 is a valid explicit choice — the t=0 init state), else the
    reference recipe's default of ``iters // 2 + 1`` (the state after 7 of
    12 iterations — README.md:83 reads ``all_levels[7]``).  The single
    definition — MFU/breakdown accounting must use the same resolution or
    their executed-iteration counts silently drift from the step fn's."""
    t = train.loss_timestep if train.loss_timestep is not None else iters // 2 + 1
    if not 0 <= t <= iters:
        raise ValueError(f"loss_timestep {t} outside [0, {iters}]")
    return t


def make_loss_fn(config: GlomConfig, train: TrainConfig, *, consensus_fn=None,
                 ff_fn=None, fused_fn=None, apply_fn=None, state_sharding=None):
    """loss(params, img, rng) -> (loss, recon).  Mirrors README.md:74-88.

    ``apply_fn`` overrides the forward entirely — a pipeline-parallel caller
    passes ``glom_tpu.parallel.pipeline.make_pipelined_apply(...)`` (which
    closed over its mesh/config/consensus/FF choices) and then feeds the
    resulting step fn to ``jax.jit`` itself; the contract is
    ``apply_fn(glom_params, img, iters=..., capture_timestep=t) ->
    (final, state_after_t)``."""
    iters = train.iters if train.iters is not None else config.default_iters
    timestep = resolve_loss_timestep(train, iters)

    two_views = train.consistency != "none"

    def loss_fn(params, img, rng):
        b = img.shape[0]
        if two_views:
            # two independently-noised views, batched into ONE scan forward;
            # the reconstruction target stays view 1, consistency couples the
            # two views' pooled level embeddings (reference roadmap item,
            # README.md:118-120)
            noise = jax.random.normal(rng, (2 * b,) + img.shape[1:], img.dtype)
            noised = jnp.concatenate([img, img]) + noise * train.noise_std
        else:
            noise = jax.random.normal(rng, img.shape, img.dtype) * train.noise_std
            noised = img + noise
        # capture_timestep: only the loss timestep's state is kept — the
        # (iters+1, b, n, L, d) return_all stack never exists on this path
        if apply_fn is not None:
            _, captured = apply_fn(
                params["glom"], noised, iters=iters, capture_timestep=timestep
            )
        else:
            _, captured = glom_model.apply(
                params["glom"], noised, config=config, iters=iters,
                capture_timestep=timestep, consensus_fn=consensus_fn, ff_fn=ff_fn,
                fused_fn=fused_fn, state_sharding=state_sharding,
            )
        # level selection (reference: all_levels[t][..., -1]) + decode live
        # in decoder_apply; arch='linear' is the exact reference recipe
        recon = decoder_apply(
            params["decoder"], captured[:b], config,
            arch=train.decoder, level=train.loss_level,
        )
        # accumulate the loss in AT LEAST fp32 (bf16 compute upcasts; f64
        # params keep f64 — matters for finite-difference grad checks)
        acc_dt = jnp.promote_types(recon.dtype, jnp.float32)
        loss = jnp.mean((recon.astype(acc_dt) - img.astype(acc_dt)) ** 2)
        if two_views:
            from glom_tpu.training.consistency import regularizer_from_state

            reg = regularizer_from_state(
                train.consistency,
                captured[:b],
                captured[b:],
                level=train.consistency_level,
                temperature=train.consistency_temperature,
            )
            loss = loss + train.consistency_weight * reg
        return loss, recon

    return loss_fn


def make_step_fn(
    config: GlomConfig,
    train: TrainConfig,
    tx: optax.GradientTransformation,
    *,
    consensus_fn=None,
    ff_fn=None,
    fused_fn=None,
    apply_fn=None,
    microbatch_sharding=None,
    state_sharding=None,
):
    """Un-jitted train step ``state, img -> state, metrics`` — the body the
    Trainer jits with explicit shardings/donation.

    With ``train.grad_accum_steps > 1`` the batch splits into that many
    sequential microbatches under a ``lax.scan``; gradients average before
    the single optimizer update.  For the plain denoising loss (a mean over
    the batch) this is numerically the full-batch step; batch-coupled terms
    (InfoNCE consistency) see per-microbatch negatives instead — documented
    semantics, not drift."""
    loss_fn = make_loss_fn(config, train, consensus_fn=consensus_fn, ff_fn=ff_fn,
                           fused_fn=fused_fn, apply_fn=apply_fn,
                           state_sharding=state_sharding)
    accum = train.grad_accum_steps

    def step_fn(state: DenoiseState, img: jax.Array) -> Tuple[DenoiseState, dict]:
        rng, rng_noise = jax.random.split(state.rng)
        if accum == 1:
            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, img, rng_noise
            )
        else:
            mb = img.shape[0] // accum
            micro = img.reshape(accum, mb, *img.shape[1:])
            if microbatch_sharding is not None:
                # keep each microbatch split across the data axis — without
                # this, contiguous row-chunks of a data-sharded batch land
                # on device subsets and GSPMD reshards every scan step
                micro = jax.lax.with_sharding_constraint(micro, microbatch_sharding)
            noise_keys = jax.random.split(rng_noise, accum)

            # accumulate in at-least-fp32 regardless of param dtype — bf16
            # sums would absorb small gradient components microbatch by
            # microbatch, breaking equivalence with the full-batch step
            acc_dt = lambda d: jnp.promote_types(d, jnp.float32)
            loss_dt = acc_dt(config.compute_dtype or config.param_dtype)

            def accum_body(carry, xs):
                loss_sum, grads_sum = carry
                chunk, key = xs
                (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    state.params, chunk, key
                )
                return (
                    loss_sum + l.astype(loss_dt),
                    jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(a.dtype), grads_sum, g
                    ),
                ), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, acc_dt(p.dtype)), state.params
            )
            (loss_sum, grads_sum), _ = jax.lax.scan(
                accum_body, (jnp.zeros((), loss_dt), zeros), (micro, noise_keys)
            )
            loss = loss_sum / accum
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / accum).astype(p.dtype), grads_sum, state.params
            )

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = DenoiseState(params, opt_state, state.step + 1, rng)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm}
        if train.monitor_numerics:
            # in-graph NaN/Inf summary on the grads the step already holds;
            # under a mesh the grads are post-psum, so the counts are
            # host-aggregated for free
            from glom_tpu.obs.monitors import numerics_metrics

            metrics.update(numerics_metrics(grads, loss))
        return new_state, metrics

    return step_fn


def make_train_step(
    config: GlomConfig,
    train: TrainConfig,
    tx: optax.GradientTransformation,
    *,
    donate: bool = True,
):
    """Single-device convenience: jitted ``state, img -> state, metrics``.
    Mesh-aware callers use ``make_step_fn`` and jit with shardings."""
    return jax.jit(
        make_step_fn(config, train, tx), donate_argnums=(0,) if donate else ()
    )


def load_checkpoint_state(directory: str, *, step: Optional[int] = None,
                          observer=None):
    """``(step, config, train_cfg, params)`` from a self-describing Trainer
    checkpoint dir — the FULL param tree ``{"glom": ..., "decoder": ...}``
    plus the recorded :class:`TrainConfig` (decoder arch, loss timestep /
    level — everything an inference consumer needs to reproduce the
    training-time decode path).  The ONE loader behind every inference-side
    consumer (``training.extract``, the serving engine, the islands
    example) so the checkpoint layout has a single read path.

    With ``step=None`` the newest checkpoint that passes integrity
    verification is loaded: corrupt newer steps are quarantined (counted /
    ``ckpt_corrupt``-triggered through ``observer``, an
    :class:`~glom_tpu.resilience.integrity.IntegrityObserver`) and the
    load falls back — a torn write can no longer take down a consumer
    that just wants the newest servable params.  A pinned ``step`` stays
    fail-loud.

    The recorded train dict is filtered to the fields THIS build knows:
    a checkpoint written by a newer build with extra knobs still loads
    (those knobs can't matter to a build that doesn't implement them)."""
    import dataclasses as _dc
    import json
    import os

    from glom_tpu.config import TrainConfig
    from glom_tpu.resilience import integrity

    with open(os.path.join(directory, "config.json")) as f:
        payload = json.load(f)
    config = GlomConfig.from_json_dict(payload["glom"])
    # the decoder arch changes the saved param tree — the template must
    # match what the trainer actually wrote (train config is informational
    # but authoritative for this)
    tcfg_dict = payload.get("train") or {}
    known = {f.name for f in _dc.fields(TrainConfig)}
    train_cfg = TrainConfig.from_json_dict(
        {k: v for k, v in tcfg_dict.items() if k in known}
    )
    template = init_state(
        jax.random.PRNGKey(0), config, optax.sgd(0.0),
        decoder=train_cfg.decoder,
        decoder_hidden_mult=train_cfg.decoder_hidden_mult,
    )
    step, trees = integrity.restore_with_fallback(
        directory, {"params": template.params}, step=step, observer=observer,
    )
    return step, config, train_cfg, trees["params"]


def load_checkpoint_params(directory: str):
    """``(step, config, glom_params)`` — the backbone-only convenience over
    :func:`load_checkpoint_state` (embedding extraction and the islands
    example never touch the decoder head)."""
    step, config, _train_cfg, params = load_checkpoint_state(directory)
    return step, config, params["glom"]
