"""Metrics / logging / observability.

Absent from the reference (SURVEY.md §5).  A dependency-free JSONL scalar
logger: one JSON object per line to stdout and/or a file — loss, imgs/sec,
step time, grad norm — the metrics of record in BASELINE.md.  Multi-host:
only process 0 emits.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional

import jax


class MetricLogger:
    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None):
        self._emit = jax.process_index() == 0
        self._stream = stream if stream is not None else sys.stdout
        self._file = open(path, "a") if (path and self._emit) else None
        self._t0 = time.time()

    def log(self, step: int, **scalars) -> None:
        if not self._emit:
            return
        rec = {"step": int(step), "time": round(time.time() - self._t0, 3)}
        for k, v in scalars.items():
            rec[k] = float(v)
        line = json.dumps(rec)
        print(line, file=self._stream, flush=True)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()

    def close(self) -> None:
        if self._file:
            self._file.close()
