"""Metrics / logging / observability facade.

``MetricLogger`` is what the Trainer (and CLI) log through: one record per
logging boundary, fanned out to pluggable exporters
(``glom_tpu.obs.exporters``).  The default configuration keeps the
historical format — JSONL to stdout plus an optional append-mode file,
floats now rounded to 6 significant digits — so every existing consumer
(``tools/plateau_report.py``, ``tools/sweep_log.py``,
``docs/runs/*.jsonl``) keeps working unchanged.

Record values: ints and bools pass through, floats are rounded for log
compactness, strings pass through (the ``event`` field is a string from the
``glom_tpu.obs.registry`` vocabulary — the old magic floats 1.0/2.0 are
retired).  Multi-host: only process 0 emits.

Deterministic file lifecycle: ``close()`` flushes and closes every
exporter's handle (context-manager protocol supported; the Trainer calls
``close()`` on every fit() exit path).  ``close`` is idempotent, and a
``log`` after ``close`` transparently reopens file sinks in append mode —
so a Trainer running fit() twice on one logger keeps appending to the same
file instead of crashing on a closed handle.
"""

from __future__ import annotations

import sys
import time
from typing import IO, Callable, Optional

import jax

from glom_tpu.obs.exporters import JsonlExporter, normalize_scalar


class MetricLogger:
    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None,
                 exporters=None, registry=None,
                 clock: Optional[Callable[[], float]] = None):
        self._emit = jax.process_index() == 0
        self.registry = registry
        self._exporters = []
        if self._emit:
            self._exporters.append(
                JsonlExporter(path=path, stream=stream if stream is not None else sys.stdout)
            )
            if exporters:
                self._exporters.extend(exporters)
        # injectable clock (obs.tracing.Tracer pattern): record `time`
        # fields are deterministic under a fake clock in tests
        self._clock = clock if clock is not None else time.time
        self._t0 = self._clock()

    def add_exporter(self, exporter) -> None:
        """Attach an additional sink (process-0 only — on other hosts this
        is a no-op, matching the emit gate).  Attaching a second exporter
        of the same class on the same path is a no-op too: two Trainers
        sharing one logger must not double-write (or race rewrites of)
        the same file."""
        if not self._emit:
            return
        path = getattr(exporter, "path", None)
        if path is not None and any(
            type(e) is type(exporter) and getattr(e, "path", None) == path
            for e in self._exporters
        ):
            return
        self._exporters.append(exporter)

    def log(self, step: int, **scalars) -> None:
        if not self._emit:
            return
        rec = {"step": int(step), "time": round(self._clock() - self._t0, 3)}
        for k, v in scalars.items():
            rec[k] = normalize_scalar(v)
        for ex in self._exporters:
            if getattr(ex, "wants_registry", False):
                ex.emit(rec, registry=self.registry)
            else:
                ex.emit(rec)

    def close(self) -> None:
        for ex in self._exporters:
            ex.close()

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
