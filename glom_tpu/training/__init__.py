"""Training subsystem.

The reference ships its training recipe as README documentation only
(`/root/reference/README.md:56-112`) — no loop, no optimizer, no data, no
metrics.  Here it is framework code: the denoising-SSL objective
(``denoise.py``), a mesh-aware jitted train step and loop (``trainer.py``),
data pipelines (``data.py``), and JSONL metrics (``metrics.py``).
"""

from glom_tpu.training.denoise import make_loss_fn, make_step_fn, make_train_step, DenoiseState
from glom_tpu.training.trainer import Trainer

__all__ = ["make_loss_fn", "make_step_fn", "make_train_step", "DenoiseState", "Trainer"]
