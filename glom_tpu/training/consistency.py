"""Contrastive / consistency regularization of top-ish levels.

This is the reference's OWN unfinished roadmap item
(`/root/reference/README.md:118-120`: "Todo: contrastive / consistency
regularization of top-ish levels") — implemented here as a framework
feature.  Two independently-noised views of each image run through the
model (batched together so it is still one scan); their level states at a
chosen (timestep, level) are pooled per image and pulled together:

  * ``mse``     — plain consistency: mean-squared distance between the two
                  views' pooled embeddings (BYOL-style without a predictor).
  * ``infonce`` — contrastive: symmetric InfoNCE over the batch with the
                  other view's embedding as the positive and the rest of the
                  batch as negatives (temperature-scaled cosine logits).

Combined objective: ``denoise_mse + weight * consistency``.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from glom_tpu.ops.consensus import l2_normalize


def pooled_level_embedding(all_levels: jax.Array, timestep: int, level: int) -> jax.Array:
    """``(T+1, b, n, L, d)`` return_all stack -> ``(b, d)`` mean-pooled
    embedding of ``level`` at ``timestep``."""
    return jnp.mean(all_levels[timestep, :, :, level], axis=1)


def pooled_state_embedding(state: jax.Array, level: int) -> jax.Array:
    """``(b, n, L, d)`` single-timestep state -> ``(b, d)`` mean-pooled
    embedding of ``level`` (the capture_timestep fast path's form)."""
    return jnp.mean(state[:, :, level], axis=1)


def consistency_loss(z1: jax.Array, z2: jax.Array) -> jax.Array:
    """MSE consistency between two views' pooled embeddings (``(b, d)``)."""
    return jnp.mean((z1.astype(jnp.float32) - z2.astype(jnp.float32)) ** 2)


def infonce_loss(z1: jax.Array, z2: jax.Array, temperature: float = 0.1) -> jax.Array:
    """Symmetric InfoNCE: for each image, the other view is the positive,
    other images (both views' logits rows) are negatives."""
    z1 = l2_normalize(z1.astype(jnp.float32))
    z2 = l2_normalize(z2.astype(jnp.float32))
    logits = z1 @ z2.T / temperature                    # (b, b)
    labels = jnp.arange(z1.shape[0])
    l12 = -jnp.mean(jax.nn.log_softmax(logits, axis=-1)[labels, labels])
    l21 = -jnp.mean(jax.nn.log_softmax(logits.T, axis=-1)[labels, labels])
    return 0.5 * (l12 + l21)


def regularizer(
    kind: str,
    all_levels_v1: jax.Array,
    all_levels_v2: jax.Array,
    *,
    timestep: int,
    level: int = -1,
    temperature: float = 0.1,
) -> jax.Array:
    """Dispatch on ``kind`` ('mse' | 'infonce') over return_all stacks."""
    return regularizer_from_state(
        kind, all_levels_v1[timestep], all_levels_v2[timestep],
        level=level, temperature=temperature,
    )


def regularizer_from_state(
    kind: str,
    state_v1: jax.Array,
    state_v2: jax.Array,
    *,
    level: int = -1,
    temperature: float = 0.1,
) -> jax.Array:
    """Same dispatch over single-timestep ``(b, n, L, d)`` states (the
    training fast path — no full-trajectory stack exists)."""
    z1 = pooled_state_embedding(state_v1, level)
    z2 = pooled_state_embedding(state_v2, level)
    if kind == "mse":
        return consistency_loss(z1, z2)
    if kind == "infonce":
        return infonce_loss(z1, z2, temperature)
    raise ValueError(f"unknown consistency kind {kind!r}")
