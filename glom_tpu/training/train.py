"""CLI training entry point: ``python -m glom_tpu.training.train``.

The reference has no launcher/CLI at all (SURVEY.md §1 'scheduler/runtime/
CLI: absent').  Flags mirror GlomConfig/TrainConfig field names 1:1.
"""

from __future__ import annotations

import argparse

import jax

from glom_tpu.config import GlomConfig, TrainConfig
from glom_tpu.parallel.mesh import initialize_distributed
from glom_tpu.training.data import AUGMENT_KINDS, make_batches
from glom_tpu.training.metrics import MetricLogger
from glom_tpu.training.trainer import Trainer


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="GLOM denoising-SSL training (TPU-native)")
    # model
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--levels", type=int, default=6)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--patch-size", type=int, default=14)
    p.add_argument("--consensus-self", action="store_true")
    p.add_argument("--local-consensus-radius", type=int, default=0)
    p.add_argument("--bf16", action="store_true", help="bf16 compute (params stay fp32)")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", default="dots", choices=["full", "dots"],
                   help="what the scan-body checkpoint saves (dots = keep "
                        "matmul outputs, recompute only elementwise)")
    p.add_argument("--attention-impl", default="dense", choices=["auto", "dense", "pallas", "ring", "ulysses"])
    p.add_argument("--ff-impl", default="dense",
                   choices=["dense", "pallas", "fused"],
                   help="fused = the single-launch level-update kernel "
                        "(consensus + both FFs in one Pallas call); falls "
                        "back to the unfused pallas pair where its shape "
                        "predicates or the mesh don't support it")
    p.add_argument("--fused-ff-bwd", action="store_true",
                   help="with --ff-impl pallas: gradients via the fused Pallas "
                        "backward kernels (hidden recomputed in VMEM) instead "
                        "of the default XLA einsum VJP")
    p.add_argument("--fuse-ff", action="store_true",
                   help="bottom_up+top_down as one grouped call per iteration")
    p.add_argument("--scan-unroll", type=int, default=1,
                   help="iteration-scan unroll factor (XLA fuses/overlaps "
                        "across iterations at >1)")
    # training
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--grad-accum-steps", type=int, default=1)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--lr-schedule", default="constant", choices=["constant", "cosine"])
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument("--grad-clip-norm", type=float, default=0.0,
                   help="clip gradients by global norm before the optimizer "
                        "(0 = off); logged grad_norm stays pre-clip")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--loss-timestep", type=int, default=None,
                   help="which trajectory state feeds the denoising loss "
                        "(reference README.md:83 reads t=7 of 12); default "
                        "iters//2+1 — also the executed-iteration count of "
                        "the capture fast path")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--stop-poll-steps", type=int, default=10,
                   help="multi-process preemption-flag poll cadence (steps); "
                        "lower it when step times are multi-second so "
                        "SIGTERM-to-checkpoint latency stays inside the "
                        "preemption grace window")
    p.add_argument("--eval-every", type=int, default=0,
                   help="log denoising PSNR every N steps (0 = off)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--noise-std", type=float, default=1.0)
    p.add_argument("--consistency", default="none", choices=["none", "mse", "infonce"],
                   help="two-view consistency regularization of top-ish levels")
    p.add_argument("--consistency-weight", type=float, default=0.1)
    p.add_argument("--consistency-temperature", type=float, default=0.1)
    p.add_argument("--consistency-level", type=int, default=-1)
    p.add_argument("--decoder", default="linear",
                   choices=["linear", "mlp", "linear_all", "mlp_all"],
                   help="reconstruction head: 'linear' = the reference "
                        "recipe (one Linear on one level); the others "
                        "strengthen only the decode path (decoder-"
                        "bottleneck A/B)")
    p.add_argument("--decoder-hidden-mult", type=int, default=2,
                   help="mlp decoder hidden width = mult * dim")
    # data
    p.add_argument("--data", default="synthetic",
                   choices=["synthetic", "folder", "images"],
                   help="synthetic randn stream, .npy/.npz folder, or a "
                        "JPEG/PNG folder tree (sharded, resumable stream)")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--augment", default="none", choices=list(AUGMENT_KINDS))
    p.add_argument("--eval-holdout", type=float, default=0.02,
                   help="(images + --eval-every) fraction of files held out "
                        "of training for the eval suite")
    p.add_argument("--probe-examples", type=int, default=256,
                   help="held-out labeled examples for the linear probe "
                        "(0 disables the probe)")
    p.add_argument("--probe-l2-grid", type=float, nargs="+", default=None,
                   help="candidate ridge strengths for the probe, chosen on "
                        "a held-out tail of the probe-train half (default: "
                        "fixed l2=1e-3)")
    p.add_argument("--eval-max-images", type=int, default=1024,
                   help="cap on held-out images decoded into host RAM and "
                        "scored per eval point (ImageNet-scale holdouts "
                        "would otherwise decode GBs per process)")
    # parallelism
    p.add_argument("--mesh", type=int, nargs="+", default=None,
                   help="mesh shape over (data, model, seq); default: all-data")
    p.add_argument("--param-sharding", default="tp", choices=["tp", "ep", "replicated"],
                   help="how params use the model axis")
    # checkpointing / logging
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--checkpoint-backend", default="npz", choices=["npz", "orbax", "sharded"])
    p.add_argument("--async-checkpoint", action="store_true",
                   help="npz backend: write checkpoints on a background "
                        "thread (host snapshot stays synchronous)")
    p.add_argument("--profile-dir", default=None,
                   help="emit a jax.profiler trace of a 3-step window here")
    p.add_argument("--trace-dir", default=None,
                   help="write the step loop's phase spans as a Perfetto-"
                        "loadable trace-event JSON here at fit() end")
    p.add_argument("--log-file", default=None)
    # observability (glom_tpu.obs)
    p.add_argument("--metrics-csv", default=None,
                   help="also mirror every log record to this CSV file")
    p.add_argument("--prom-textfile", default=None,
                   help="write a Prometheus textfile-collector snapshot "
                        "here at every log boundary (atomic rename)")
    p.add_argument("--diag-every", type=int, default=0,
                   help="GLOM-level diagnostics cadence (island agreement, "
                        "attention entropy, contribution shares) — one "
                        "extra forward every N steps; 0 = off")
    p.add_argument("--no-monitor-numerics", action="store_true",
                   help="disable the in-graph NaN/Inf + grad-spike monitor "
                        "(on by default; costs a few reductions per step)")
    p.add_argument("--grad-spike-factor", type=float, default=10.0,
                   help="flag a window when grad_norm exceeds this factor "
                        "times its running EMA")
    # resilience (glom_tpu.resilience)
    p.add_argument("--halt-on-nan", action="store_true",
                   help="fail fast when a numerics window shows nonfinite "
                        "grads/loss, before poisoned params can be "
                        "checkpointed (pairs with --supervise)")
    p.add_argument("--supervise", action="store_true",
                   help="run fit() under the self-healing supervisor: "
                        "crashes restart with exponential backoff from the "
                        "newest checkpoint that passes integrity "
                        "verification; a crash loop gives up loudly")
    p.add_argument("--max-restart-failures", type=int, default=5,
                   help="(--supervise) failures within the crash-loop "
                        "window before giving up")
    p.add_argument("--restart-window-s", type=float, default=600.0,
                   help="(--supervise) sliding crash-loop window, seconds")
    # forensics (glom_tpu.obs.forensics): anomaly-triggered evidence capture
    p.add_argument("--forensics-dir", default=None,
                   help="write post-mortem bundles (flight-recorder ring, "
                        "env fingerprint, HLO/cost snapshot) here when a "
                        "monitor fires, the run crashes, or preemption "
                        "stops it; None = no bundles (the in-memory "
                        "flight recorder still records)")
    p.add_argument("--forensics-ring", type=int, default=256,
                   help="flight-recorder capacity in log records (0 = off)")
    p.add_argument("--forensics-max-captures", type=int, default=3,
                   help="global per-run budget of triggered captures")
    p.add_argument("--forensics-debounce-steps", type=int, default=200,
                   help="per-trigger re-fire spacing: a NaN storm inside "
                        "this many steps is one bundle, not one per window")
    p.add_argument("--forensics-trace-steps", type=int, default=0,
                   help="also record a jax.profiler trace of N steps after "
                        "each capture (0 = off; tens of MB per capture; "
                        "ignored while --profile-dir is set)")
    p.add_argument("--no-forensics-hlo", action="store_true",
                   help="skip the HLO + cost/memory-analysis snapshot in "
                        "bundles (it may pay a compile at capture time)")
    p.add_argument("--forensics-step-time-factor", type=float, default=2.0,
                   help="fire the step-time regression trigger when recent "
                        "windows' p95 per-step train time exceeds this "
                        "factor times the rolling baseline p95 (0 = off)")
    # multi-host
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    # platform
    p.add_argument("--platform", default="auto",
                   help="force a JAX platform (e.g. 'cpu') instead of the "
                        "auto-detected accelerator; 'auto' keeps the default. "
                        "Set via jax.config (env JAX_PLATFORMS can be "
                        "overridden by site plugins)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    initialize_distributed(args.coordinator, args.num_processes, args.process_id)

    import jax.numpy as jnp

    config = GlomConfig(
        dim=args.dim,
        levels=args.levels,
        image_size=args.image_size,
        patch_size=args.patch_size,
        consensus_self=args.consensus_self,
        local_consensus_radius=args.local_consensus_radius,
        compute_dtype=jnp.bfloat16 if args.bf16 else None,
        remat=args.remat,
        remat_policy=args.remat_policy,
        attention_impl=args.attention_impl,
        ff_impl=args.ff_impl,
        ff_fused_bwd=args.fused_ff_bwd,
        fuse_ff=args.fuse_ff,
        scan_unroll=args.scan_unroll,
    )
    train_cfg = TrainConfig(
        batch_size=args.batch_size,
        grad_accum_steps=args.grad_accum_steps,
        learning_rate=args.lr,
        lr_schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        weight_decay=args.weight_decay,
        grad_clip_norm=args.grad_clip_norm,
        iters=args.iters,
        loss_timestep=args.loss_timestep,
        noise_std=args.noise_std,
        consistency=args.consistency,
        consistency_weight=args.consistency_weight,
        consistency_temperature=args.consistency_temperature,
        consistency_level=args.consistency_level,
        decoder=args.decoder,
        decoder_hidden_mult=args.decoder_hidden_mult,
        steps=args.steps,
        log_every=args.log_every,
        stop_poll_steps=args.stop_poll_steps,
        eval_every=args.eval_every,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_backend=args.checkpoint_backend,
        async_checkpoint=args.async_checkpoint,
        profile_dir=args.profile_dir,
        trace_dir=args.trace_dir,
        monitor_numerics=not args.no_monitor_numerics,
        grad_spike_factor=args.grad_spike_factor,
        halt_on_nan=args.halt_on_nan,
        diag_every=args.diag_every,
        forensics_dir=args.forensics_dir,
        forensics_ring=args.forensics_ring,
        forensics_max_captures=args.forensics_max_captures,
        forensics_debounce_steps=args.forensics_debounce_steps,
        forensics_trace_steps=args.forensics_trace_steps,
        forensics_hlo=not args.no_forensics_hlo,
        forensics_step_time_factor=args.forensics_step_time_factor,
        metrics_csv=args.metrics_csv,
        prom_textfile=args.prom_textfile,
        seed=args.seed,
        mesh_shape=tuple(args.mesh) if args.mesh else None,
        param_sharding=args.param_sharding,
    )

    eval_data = None
    train_files = None
    if args.data == "images" and args.eval_every:
        # carve a held-out split BEFORE the training stream exists, so eval
        # images never enter the step function (VERDICT r1 item 6)
        from glom_tpu.training.eval import holdout_split
        from glom_tpu.training.image_stream import (
            labels_from_paths, list_image_files, load_images,
        )

        import numpy as np

        train_files, eval_files = holdout_split(
            list_image_files(args.data_dir), args.eval_holdout, seed=args.seed
        )
        # eval_files arrive class-grouped (sorted paths); permute before the
        # RAM/probe caps so the decoded subset spans classes, and bound the
        # decode cost (an uncapped 2% ImageNet holdout is ~15 GB fp32/host)
        perm = np.random.default_rng(args.seed).permutation(len(eval_files))
        eval_files = [eval_files[i] for i in perm[:args.eval_max_images]]
        eval_imgs = load_images(eval_files, args.image_size)
        probe_kwargs = {}
        if args.probe_examples:
            probe_files = eval_files[:args.probe_examples]
            labels, names = labels_from_paths(probe_files)
            if len(names) > 1:
                probe_kwargs = dict(
                    probe_images=eval_imgs[:args.probe_examples],
                    probe_labels=labels, num_classes=len(names),
                    probe_l2_grid=args.probe_l2_grid,
                )
        eval_data = (eval_imgs, probe_kwargs)

    def make_stream():
        if train_files is not None:
            from glom_tpu.training.data import _StatefulAugmented
            from glom_tpu.training.image_stream import ImageFolderStream

            stream = ImageFolderStream(
                args.data_dir, args.batch_size, args.image_size,
                channels=config.channels, seed=args.seed, files=train_files,
            )
            if args.augment != "none":
                stream = _StatefulAugmented(stream, args.augment, args.seed)
            return stream
        return make_batches(
            args.data, args.batch_size, args.image_size,
            config.channels, args.seed, args.data_dir,
            augment=args.augment,
        )

    def run_once():
        # rebuilt fresh per (supervised) attempt: a crashed attempt's
        # trainer/state/iterator may be poisoned — recovery state flows
        # only through the checkpoint directory
        trainer = Trainer(config, train_cfg, logger=MetricLogger(path=args.log_file))
        if eval_data is not None:
            # built after the Trainer so the suite shares its mesh-bound
            # consensus/FF implementations (ring/ulysses/sharded-pallas)
            from glom_tpu.training.eval import EvalSuite

            eval_imgs, probe_kwargs = eval_data
            trainer.set_eval_suite(EvalSuite(
                config, eval_imgs, noise_std=args.noise_std, iters=args.iters,
                timestep=args.loss_timestep,  # PSNR scores the trained state
                chunk=min(args.batch_size, len(eval_imgs)),
                consensus_fn=trainer._consensus_fn, ff_fn=trainer._ff_fn,
                decoder=args.decoder,
                **probe_kwargs,
            ))
        batches = make_stream()
        try:
            return trainer.fit(batches)
        finally:
            close = getattr(batches, "close", None)
            if callable(close):
                close()

    if args.supervise:
        from glom_tpu.obs import MetricRegistry
        from glom_tpu.resilience.supervisor import RestartPolicy, Supervisor

        # the supervisor outlives every per-attempt Trainer (each attempt
        # rebuilds its own registry/forensics), so it gets its own: restart
        # counters land in each crash_restart bundle's metrics.json
        sup_registry = MetricRegistry()
        sup_forensics = None
        if args.forensics_dir:
            from glom_tpu.obs import ForensicsManager

            sup_forensics = ForensicsManager(
                args.forensics_dir, registry=sup_registry,
                config={"glom": config.to_json_dict(),
                        "train": train_cfg.to_json_dict()},
            )
        final = Supervisor(
            run_once,
            policy=RestartPolicy(
                max_failures=args.max_restart_failures,
                window_s=args.restart_window_s,
            ),
            checkpoint_dir=args.checkpoint_dir,
            registry=sup_registry,
            forensics=sup_forensics,
            seed=args.seed,
        ).run()
    else:
        final = run_once()
    if jax.process_index() == 0:
        print({"final": final})


if __name__ == "__main__":
    main()
