"""Mesh-aware training loop.

The framework-owned replacement for the user-written loop the reference
documents (`README.md:56-90`): builds the device mesh, places the training
state with the sharding rules from ``glom_tpu.parallel``, jits the denoising
step with donated state (grad psum over ICI is emitted by XLA from the
shardings — pure-DP by default, TP/SP when the mesh says so), and runs the
step loop with JSONL metrics and checkpoint/resume.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from glom_tpu import checkpoint as ckpt_lib
from glom_tpu.config import GlomConfig, TrainConfig
from glom_tpu.obs import (
    EVENT_FORENSICS,
    EVENT_NAN,
    EVENT_PREEMPT_STOP,
    EVENT_RECOMPILE,
    EVENT_RESUME,
    MemoryMonitor,
    MetricRegistry,
    NumericsMonitor,
    PhaseTimer,
    RecompileMonitor,
    flatten_diagnostics,
)
from glom_tpu.obs.triggers import (
    TRIGGER_CRASH,
    TRIGGER_GRAD_SPIKE,
    TRIGGER_NAN,
    TRIGGER_PREEMPT,
    TRIGGER_RECOMPILE,
    TRIGGER_STEP_TIME,
)
from glom_tpu.parallel.mesh import make_mesh
from glom_tpu.parallel.placement import state_shardings
from glom_tpu.resilience import integrity
from glom_tpu.parallel.sharding import batch_pspec, param_pspecs
from glom_tpu.training import denoise
from glom_tpu.training.metrics import MetricLogger


class NonFiniteError(RuntimeError):
    """Raised (with ``TrainConfig.halt_on_nan``) when a numerics window
    shows nonfinite grads/loss: continuing would train on poisoned
    parameters and eventually CHECKPOINT them, destroying the resume
    lineage.  Failing fast here is what lets a supervisor
    (:mod:`glom_tpu.resilience.supervisor`) restart from the last clean
    checkpoint."""


def _decoder_specs(arch: str = "linear") -> dict:
    """Replicated specs matching heads.decoder_init's tree for ``arch``
    (the decoder is tiny; it never shards)."""
    if arch in ("mlp", "mlp_all"):
        return {"w1": P(None, None), "b1": P(None),
                "w2": P(None, None), "b2": P(None)}
    return {"w": P(None, None), "b": P(None)}


def make_lr_schedule(train: TrainConfig):
    """Resolve TrainConfig's learning-rate schedule into an optax schedule
    (or a constant float)."""
    if train.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=train.learning_rate,
            warmup_steps=max(train.warmup_steps, 1),
            decay_steps=max(train.steps, train.warmup_steps + 1),
        )
    return train.learning_rate


class Trainer:
    def __init__(
        self,
        config: GlomConfig,
        train: TrainConfig,
        *,
        mesh: Optional[Mesh] = None,
        tx: Optional[optax.GradientTransformation] = None,
        logger: Optional[MetricLogger] = None,
        eval_suite=None,
    ):
        self.config = config
        self.train_cfg = train
        self.mesh = mesh if mesh is not None else make_mesh(train.mesh_shape, train.mesh_axes)
        if tx is None:
            lr = make_lr_schedule(train)
            tx = (
                optax.adamw(lr, weight_decay=train.weight_decay)
                if train.weight_decay
                else optax.adam(lr)
            )
            if train.grad_clip_norm:
                # clip BEFORE the optimizer (the standard order); the logged
                # grad_norm metric stays the raw pre-clip norm
                tx = optax.chain(
                    optax.clip_by_global_norm(train.grad_clip_norm), tx
                )
        self.tx = tx
        # ONE registry per trainer+logger pair: every monitor and exporter
        # reports through it, and the Prometheus textfile (when
        # configured) is its rendered snapshot.  A caller-supplied
        # logger's registry is ADOPTED — two registries would split the
        # metrics between what the trainer instruments and what the
        # exporters render.
        self.logger = logger or MetricLogger()
        self.registry = getattr(self.logger, "registry", None) or MetricRegistry()
        # duck-typed custom loggers only owe log()/close(); the registry
        # handoff and config-driven exporters apply when they speak the
        # MetricLogger protocol
        if getattr(self.logger, "registry", "absent") is None:
            self.logger.registry = self.registry
        if hasattr(self.logger, "add_exporter"):
            if train.metrics_csv:
                from glom_tpu.obs import CsvExporter

                self.logger.add_exporter(CsvExporter(train.metrics_csv))
            if train.prom_textfile:
                from glom_tpu.obs import PrometheusTextfileExporter

                self.logger.add_exporter(
                    PrometheusTextfileExporter(train.prom_textfile)
                )

        if len(train.mesh_axes) < 2:
            raise ValueError(
                f"mesh_axes needs at least (data, model) axes, got {train.mesh_axes}; "
                "use mesh_shape=(N, 1, 1) for pure DP"
            )
        data_axis, model_axis = train.mesh_axes[0], train.mesh_axes[1]
        if train.batch_size % self.mesh.shape[data_axis] != 0:
            raise ValueError(
                f"batch_size {train.batch_size} not divisible by data-axis size "
                f"{self.mesh.shape[data_axis]}"
            )
        microbatch = train.batch_size // train.grad_accum_steps
        if microbatch % self.mesh.shape[data_axis] != 0:
            raise ValueError(
                f"microbatch {microbatch} (batch {train.batch_size} / "
                f"grad_accum_steps {train.grad_accum_steps}) not divisible by "
                f"data-axis size {self.mesh.shape[data_axis]}"
            )

        # attention_impl='auto' is mesh-aware here (VERDICT r3 item 7): a
        # real seq axis means the trainer was asked for sequence parallelism,
        # so auto resolves to the ring (ppermute) consensus — the n-column
        # state is sharded over 'seq' and a dense/pallas consensus would
        # silently all-gather it.  Ring over Ulysses because ring has no
        # L % seq constraint (Ulysses shards the level axis as heads).
        # Without a >1 seq axis, model-level auto applies (dense at n<=256,
        # pallas above — BASELINE.md round-2 measurement).
        if config.attention_impl == "auto" and len(train.mesh_axes) > 2:
            seq_size = self.mesh.shape.get(train.mesh_axes[2], 1)
            if seq_size > 1:
                if config.num_patches % seq_size == 0:
                    import dataclasses

                    config = dataclasses.replace(config, attention_impl="ring")
                    self.config = config
                else:
                    import warnings

                    warnings.warn(
                        f"attention_impl='auto' cannot resolve to the ring "
                        f"consensus: num_patches {config.num_patches} not "
                        f"divisible by seq-axis size {seq_size} — falling "
                        f"back to the model-level rule, which all-gathers "
                        f"the seq-sharded state (no sequence parallelism in "
                        f"the consensus)",
                        stacklevel=2,
                    )

        # Trailing mesh axes past (data, model, seq) are additional expert-
        # axis factors under 'ep': levels and levels-1 are coprime, so a
        # factored model axis (e.g. 3x2) is the only way to expert-shard
        # BOTH nets evenly (see level_sharded_pspecs).  Computed ONCE here —
        # the param specs and the Pallas shard_map must see the same tuple.
        expert_axes = tuple(
            a for a in train.mesh_axes[3:] if self.mesh.shape[a] > 1
        )

        if train.param_sharding == "tp":
            glom_specs = param_pspecs(config, model_axis=model_axis)
        elif train.param_sharding == "ep":
            from glom_tpu.parallel.sharding import level_sharded_pspecs

            glom_specs = level_sharded_pspecs(
                config, model_axis=model_axis,
                axis_size=self.mesh.shape[model_axis],
                extra_axes={a: self.mesh.shape[a] for a in expert_axes} or None,
            )
        else:  # replicated
            glom_specs = jax.tree_util.tree_map(
                lambda _: P(), param_pspecs(config), is_leaf=lambda x: isinstance(x, P)
            )
        spec_tree = {"glom": glom_specs, "decoder": _decoder_specs(train.decoder)}
        rng = jax.random.PRNGKey(train.seed)

        def _init():
            return denoise.init_state(
                rng, config, tx, decoder=train.decoder,
                decoder_hidden_mult=train.decoder_hidden_mult,
            )

        abstract = jax.eval_shape(_init)
        self._state_sh = state_shardings(self.mesh, abstract, spec_tree)
        self._batch_sh = NamedSharding(self.mesh, batch_pspec(data_axis))

        init_fn = jax.jit(_init, out_shardings=self._state_sh)
        self.state = init_fn()

        ff_fn = None
        fused_fn = None
        if config.ff_impl in ("pallas", "fused") and self.mesh.devices.size > 1:
            from glom_tpu.models.glom import fused_update_supported

            seq_ax_name = train.mesh_axes[2] if len(train.mesh_axes) > 2 else None
            seq_sharded = (seq_ax_name is not None
                           and self.mesh.shape.get(seq_ax_name, 1) > 1)
            params_sharded = (train.param_sharding != "replicated"
                              and self.mesh.shape[model_axis] > 1)
            if (config.ff_impl == "fused" and fused_update_supported(config)
                    and not seq_sharded and not params_sharded):
                # pure DP / replicated params: the whole update runs as one
                # Pallas launch per shard (parallel/fused_shard.py).  Any
                # seq/TP/EP sharding is structurally incompatible with the
                # one-shot consensus + whole-net weight blocks — those
                # meshes fall through to the proven sharded unfused pair.
                from glom_tpu.parallel.fused_shard import make_sharded_fused_update

                fused_fn = make_sharded_fused_update(
                    self.mesh, config, data_axis=data_axis,
                )
            else:
                if config.ff_impl == "fused":
                    import warnings

                    warnings.warn(
                        "ff_impl='fused' does not support this mesh/shape "
                        "(seq- or model-sharded, or supports_config failed); "
                        "falling back to the sharded unfused pallas FF",
                        stacklevel=2,
                    )
                # pallas_call is opaque to GSPMD — run the kernel inside a
                # shard_map matching the actual param/batch placements so
                # each device sees only its shard (TP gets the row-parallel
                # psum)
                from glom_tpu.parallel.ff_shard import make_sharded_ff_pallas

                ff_fn = make_sharded_ff_pallas(
                    self.mesh,
                    param_sharding=train.param_sharding,
                    data_axis=data_axis,
                    model_axis=model_axis,
                    seq_axis=seq_ax_name,
                    fused_bwd=config.ff_fused_bwd,
                    extra_expert_axes=expert_axes,
                )
        self._ff_fn = ff_fn
        self._fused_fn = fused_fn

        consensus_fn = None
        if config.attention_impl in ("ring", "ulysses"):
            from glom_tpu.models.glom import resolve_locality_mask

            if len(train.mesh_axes) < 3:
                raise ValueError(
                    f"attention_impl={config.attention_impl!r} needs a third "
                    f"(seq) mesh axis; got mesh_axes={train.mesh_axes}"
                )
            if config.attention_impl == "ring":
                from glom_tpu.parallel.ring import make_ring_consensus as make_sp
            else:
                from glom_tpu.parallel.ulysses import make_ulysses_consensus as make_sp
            consensus_fn = make_sp(
                self.mesh,
                attend_self=config.consensus_self,
                non_local_mask=resolve_locality_mask(config),
                data_axis=data_axis,
                seq_axis=train.mesh_axes[2],
            )
        self._consensus_fn = consensus_fn

        # Pin the (b, n, L, d) scan carry to the activation layout (batch
        # over data, columns over seq) so expert-sharded param layouts can
        # never propagate onto the carried state — see glom.apply's
        # state_sharding doc for the factored-EP failure mode this blocks.
        act_sh = None
        if self.mesh.devices.size > 1:
            seq_ax = train.mesh_axes[2] if len(train.mesh_axes) > 2 else None
            act_sh = NamedSharding(self.mesh, P(data_axis, seq_ax))
        self._act_sh = act_sh

        self._eval_suite = eval_suite
        self._eval = None
        if train.eval_every and eval_suite is None:
            from glom_tpu.training.eval import make_psnr_fn

            self._eval = jax.jit(
                make_psnr_fn(
                    config, noise_std=train.noise_std, iters=train.iters,
                    timestep=train.loss_timestep, level=train.loss_level,
                    consensus_fn=consensus_fn, ff_fn=ff_fn, fused_fn=fused_fn,
                    state_sharding=act_sh, decoder=train.decoder,
                )
            )

        micro_sh = None
        if train.grad_accum_steps > 1:
            micro_sh = NamedSharding(self.mesh, P(None, data_axis))

        self._step = jax.jit(
            denoise.make_step_fn(
                config, train, tx, consensus_fn=consensus_fn, ff_fn=ff_fn,
                fused_fn=fused_fn, microbatch_sharding=micro_sh,
                state_sharding=act_sh,
            ),
            in_shardings=(self._state_sh, self._batch_sh),
            out_shardings=(self._state_sh, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if train.donate else (),
        )

        # -- runtime health monitors (glom_tpu.obs) --
        self._recompile_mon = RecompileMonitor(self._step)
        self._mem_mon = MemoryMonitor()
        self._num_mon = NumericsMonitor(spike_factor=train.grad_spike_factor)

        # -- step-scoped span tracing (glom_tpu.obs.tracing) --
        # The PhaseTimer records each phase interval as a span under a
        # per-window `train_window` trace — the same span format the
        # serving path emits, so one Perfetto viewer (and one
        # tools/trace_report.py) reads both.  Host-side dicts in a bounded
        # sink; no device syncs.
        from glom_tpu.obs import TraceSink, Tracer

        # one window trace holds ~9 phase spans per step for log_every
        # steps; the default 512-span cap would silently truncate windows
        # past ~60 steps
        self.tracer = Tracer(registry=self.registry, sink=TraceSink(
            max_spans=max(512, 12 * (train.log_every or 1) + 16)))

        # -- anomaly-triggered forensics (glom_tpu.obs.forensics) --
        # The flight recorder tees every logged record into a bounded ring
        # (host-side dict copies at the LOGGING cadence — no per-step
        # device sync).  Bundles, triggers, and the step-time regression
        # detector only exist when forensics_dir is set; bundle writing is
        # leader-only, matching the logging gate.
        self._recorder = None
        self._forensics = None
        self._triggers = None
        self._steptime_mon = None
        self._last_batch_spec = None
        if train.forensics_ring:
            from glom_tpu.obs import FlightRecorder

            self._recorder = FlightRecorder(capacity=train.forensics_ring)
        if train.forensics_dir and jax.process_index() == 0:
            from glom_tpu.obs import (
                ForensicsManager,
                StepTimeRegressionMonitor,
                TriggerEngine,
            )

            self._triggers = TriggerEngine(
                debounce_steps=train.forensics_debounce_steps,
                max_captures=train.forensics_max_captures,
                registry=self.registry,
            )
            self._forensics = ForensicsManager(
                train.forensics_dir,
                recorder=self._recorder,
                config={"glom": self.config.to_json_dict(),
                        "train": train.to_json_dict()},
                mesh=self.mesh,
                # profile_dir's always-on trace owns the profiler: two
                # concurrent jax traces cannot coexist
                trace_steps=0 if train.profile_dir else train.forensics_trace_steps,
                snapshot_fn=self._forensics_snapshot if train.forensics_hlo else None,
                registry=self.registry,
            )
            if train.forensics_step_time_factor:
                self._steptime_mon = StepTimeRegressionMonitor(
                    factor=train.forensics_step_time_factor
                )
        # checkpoint-integrity telemetry (glom_tpu.resilience.integrity):
        # quarantines found during resume bump ckpt_corrupt_total and fire
        # the debounced ckpt_corrupt trigger into a forensics bundle
        self._integrity_obs = integrity.IntegrityObserver(
            registry=self.registry, triggers=self._triggers,
            forensics=self._forensics,
        )

        self._diag = None
        if train.diag_every:
            from glom_tpu.obs import make_diagnostics_fn

            self._diag = jax.jit(make_diagnostics_fn(
                self.config, iters=train.iters, consensus_fn=consensus_fn,
                ff_fn=ff_fn, fused_fn=fused_fn, state_sharding=act_sh,
            ))

    def set_eval_suite(self, suite) -> None:
        """Attach/replace the held-out eval suite after construction (the
        CLI builds the suite with this trainer's mesh-bound consensus/FF
        fns, which only exist once the trainer does)."""
        self._eval_suite = suite

    # -- forensics --------------------------------------------------------
    def _log(self, step, **scalars) -> None:
        """Log one record AND tee it into the flight-recorder ring, so a
        later bundle flush carries the records leading up to the anomaly.
        Every trainer log site goes through here."""
        if self._recorder is not None:
            self._recorder.record(step, scalars)
        self.logger.log(step, **scalars)

    def _forensics_snapshot(self) -> dict:
        """HLO text + compiler cost/memory analyses of the jitted step,
        from abstract args only (ShapeDtypeStructs — no device data, no
        interaction with donated buffers).  May pay a compile on a jit
        cache miss; the capture budget bounds how often."""
        from glom_tpu import profiling

        if self._last_batch_spec is None:
            return {}
        abstract_state = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.state
        )
        return profiling.compile_snapshot(
            self._step, abstract_state, self._last_batch_spec
        )

    def _maybe_capture(self, trigger: str, step: int, detail: dict) -> None:
        """Route one monitor firing through the trigger engine (debounce +
        budget) and, when accepted, write a forensics bundle.  Never
        raises."""
        if self._forensics is None:
            return
        if self._triggers is not None and not self._triggers.fire(trigger, step):
            return
        path = self._forensics.capture(trigger, step, detail)
        if path:
            self._log(step, event=EVENT_FORENSICS, trigger=trigger, bundle=path)
        elif self._triggers is not None:
            # the capture failed (warned by the manager): give the budget
            # slot back so a later genuine anomaly can still be captured
            self._triggers.refund(trigger, step)

    def _crash_capture(self, exc: BaseException) -> None:
        """Terminal-path bundle for an unhandled fit() exception: bypasses
        the trigger engine (a crash fires once by construction) but keeps
        every guard — the bundle is best-effort, the original exception is
        what must surface."""
        if self._forensics is None:
            return
        self._forensics.stop_trace()  # a triggered trace must not leak
        import traceback

        try:
            step = int(jax.device_get(self.state.step))
        except Exception:  # glomlint: disable=conc-broad-except -- crash capture: the device may be wedged mid-SIGABRT; step -1 is best-effort evidence and the bundle still ships the real traceback
            step = -1
        self._forensics.capture(
            TRIGGER_CRASH, step,
            {"error": f"{type(exc).__name__}: {exc}",
             "traceback": "".join(traceback.format_exception(
                 type(exc), exc, exc.__traceback__))},
        )

    # -- checkpointing ----------------------------------------------------
    def finish_saves(self) -> None:
        """Block until any in-flight async checkpoint write is durable.
        Re-raises the writer's exception (a swallowed ENOSPC would let fit()
        claim durability for a checkpoint that does not exist)."""
        t = getattr(self, "_ckpt_thread", None)
        if t is not None:
            t.join()
            self._ckpt_thread = None
            err = self._ckpt_error
            self._ckpt_error = None
            if err is not None:
                raise err

    def _write_config_json(self, directory: str) -> None:
        """Make the checkpoint directory self-describing: the model config
        (validated on restore) plus the training config (informational) next
        to the weights.  Leader-only, atomic, refreshed on every save so a
        resume that legitimately changes execution knobs (attention_impl,
        dtypes, lr, ...) updates the record instead of warning forever
        against a stale one.  Architecture fields may never change within a
        directory — saving a different architecture into an existing
        checkpoint dir is refused (its weights would be unloadable anyway)."""
        import json
        import os

        if jax.process_index() != 0:
            return
        path = os.path.join(directory, "config.json")
        if os.path.exists(path):
            with open(path) as f:
                recorded = json.load(f)["glom"]
            mine = self.config.to_json_dict()
            arch_diff = {
                k: (recorded.get(k), mine.get(k))
                for k in self._ARCH_FIELDS
                if recorded.get(k) != mine.get(k)
            }
            if arch_diff:
                raise ValueError(
                    f"refusing to save into {directory}: it holds checkpoints "
                    f"from a different model architecture. Differing fields "
                    f"(directory, this trainer): {arch_diff}"
                )
        os.makedirs(directory, exist_ok=True)
        payload = json.dumps(
            {"glom": self.config.to_json_dict(),
             "train": self.train_cfg.to_json_dict()},
            indent=2,
        ).encode()
        ckpt_lib._atomic_write(directory, "config.json", lambda f: f.write(payload))

    # fields that determine parameter shapes/meaning — a mismatch means the
    # weights belong to a different architecture.  Execution knobs
    # (attention_impl, remat, dtypes, ...) may legitimately change across a
    # resume: every impl is numerically interchangeable (PARITY.md).
    _ARCH_FIELDS = ("dim", "levels", "image_size", "patch_size", "channels", "ff_mult")

    def _validate_config_json(self, directory: str) -> None:
        import json
        import os

        path = os.path.join(directory, "config.json")
        if not os.path.exists(path):
            return  # pre-0.2 checkpoint dirs carry no config record
        with open(path) as f:
            recorded = json.load(f)["glom"]
        mine = self.config.to_json_dict()
        arch_diff = {
            k: (recorded.get(k), mine.get(k))
            for k in self._ARCH_FIELDS
            if recorded.get(k) != mine.get(k)
        }
        if arch_diff:
            raise ValueError(
                f"checkpoint dir {directory} was written by a different model "
                f"architecture; refusing to load its weights. Differing "
                f"fields (checkpoint, this trainer): {arch_diff}"
            )
        # iterate the RECORDED knobs only: fields added after the checkpoint
        # was written (e.g. scan_unroll on a pre-0.3 dir) are a version
        # artifact, not a changed knob, and must not warn
        other_diff = {
            k: (recorded[k], mine.get(k))
            for k in sorted(recorded)
            if k not in self._ARCH_FIELDS and recorded[k] != mine.get(k)
        }
        if other_diff:
            import warnings

            warnings.warn(
                f"resuming with different model-config knobs than the "
                f"checkpoint was trained with (checkpoint, this trainer): "
                f"{other_diff}",
                stacklevel=2,
            )

    def save(self, directory: str, *, data_state: Optional[dict] = None) -> str:
        """Checkpoint the full training state; returns the artifact path
        (leader) or "" (non-leader).  Durability contract: with
        ``async_checkpoint`` the returned npz path is named immediately but
        the background write may still be in flight — it is durable only
        after :meth:`finish_saves` returns (``fit`` drains on every exit
        path); a caller that opens the path before draining races the
        writer.  Synchronous backends return only after the write."""
        self.finish_saves()  # order manifests; bound in-flight writes to one
        self._write_config_json(directory)
        async_requested = self.train_cfg.async_checkpoint
        if async_requested and self.train_cfg.checkpoint_backend != "npz":
            import warnings

            warnings.warn(
                "async_checkpoint only applies to the npz backend (orbax is "
                "internally async; sharded writes are O(local bytes) already)"
                " — saving synchronously",
                stacklevel=2,
            )
        if self.train_cfg.checkpoint_backend == "sharded":
            # per-process shard writes: every process persists only its own
            # replica-0 tiles — no host gather, no cross-host traffic; each
            # process's data cursor is saved per-process (shards differ in
            # size, so cursors legitimately diverge across processes)
            trees = {"params": self.state.params, "opt": self.state.opt_state,
                     "rng": self.state.rng}
            if data_state is not None:
                trees["data"] = data_state
            return ckpt_lib.save_sharded(
                directory, int(jax.device_get(self.state.step)), trees,
                per_process=("data",),
            )
        if data_state is not None and jax.process_count() > 1:
            # gathered npz/orbax artifacts are leader-written: they can only
            # carry ONE cursor, which would be wrong for every other process
            import warnings

            warnings.warn(
                "data-iterator cursor is not checkpointed with "
                f"backend={self.train_cfg.checkpoint_backend!r} under "
                "multiple processes (per-process cursors diverge); use "
                "checkpoint_backend='sharded' for exact stream resume",
                stacklevel=2,
            )
            data_state = None
        if jax.process_count() > 1:
            # sharded leaves may span non-addressable devices: replicate
            # across the mesh, then read locally (cached jit per mesh)
            from glom_tpu.parallel.placement import gather_to_host

            host_state = denoise.DenoiseState(*gather_to_host(tuple(self.state), self.mesh))
        else:
            host_state = jax.device_get(self.state)
        trees = {"params": host_state.params, "opt": host_state.opt_state, "rng": host_state.rng}
        if data_state is not None:
            trees["data"] = data_state
        step = int(host_state.step)
        if async_requested and self.train_cfg.checkpoint_backend == "npz":
            # host_state above is a device_get/gather snapshot (real numpy —
            # safe even though the live buffers get donated into the next
            # step); only the serialize+write moves off-thread.  Non-daemon:
            # interpreter exit must not kill a write mid-savez.
            import threading

            self._ckpt_error = None

            def _write():
                try:
                    ckpt_lib.save(directory, step, trees, backend="npz")
                except BaseException as e:  # surfaced by finish_saves()
                    self._ckpt_error = e

            self._ckpt_thread = threading.Thread(target=_write)
            self._ckpt_thread.start()
            # same contract as the sync save: only the leader names a path
            return ckpt_lib.npz_path(directory, step) if jax.process_index() == 0 else ""
        return ckpt_lib.save(
            directory,
            step,
            trees,
            backend=self.train_cfg.checkpoint_backend,
        )

    def restore(self, directory: str, *, batches=None,
                step: Optional[int] = None) -> int:
        """Restore params, optimizer state AND the training RNG, so a resumed
        run continues the noise-key sequence instead of replaying it.  When
        ``batches`` exposes ``state_dict``/``load_state_dict`` (the
        ``ImageFolderStream`` contract) its cursor is restored too, so the
        stream resumes on the exact next batch; stateless synthetic/folder
        streams are unaffected.

        With ``step=None`` the newest checkpoint that passes integrity
        verification is restored — corrupt newer steps are quarantined
        (``*.corrupt``, counted, ``ckpt_corrupt``-triggered) and the
        restore falls back, so a torn write costs one checkpoint interval,
        not the run.  A pinned ``step`` keeps fail-loud semantics.

        If the directory carries a ``config.json`` (written by save), its
        MODEL config must match this trainer's — loading weights into a
        different architecture is refused rather than crashing downstream
        (or, worse, silently reinterpreting shapes).  The recorded training
        config is informational only (it may legitimately change)."""
        self.finish_saves()  # never read past an in-flight write
        self._validate_config_json(directory)
        step, trees = integrity.restore_with_fallback(
            directory,
            {"params": self.state.params, "opt": self.state.opt_state, "rng": self.state.rng},
            step=step, observer=self._integrity_obs,
        )
        # Launder the restored trees through a non-donating jit identity
        # BEFORE they reach the donating step.  The npz restore yields host
        # numpy arrays, and on the CPU backend both the direct jit feed and
        # ``jax.device_put`` can zero-copy alias the numpy heap allocation;
        # donating such a buffer has XLA free memory numpy still owns
        # (glibc "corrupted double-linked list", reliably fatal under
        # persistent-cache-deserialized step executables).  A jit identity
        # forces XLA-owned output buffers — donation-safe by construction —
        # and its out_shardings restore the mesh placement the step's
        # in_shardings expect.
        trees = jax.jit(
            lambda t: t,
            out_shardings={"params": self._state_sh.params,
                           "opt": self._state_sh.opt_state,
                           "rng": self._state_sh.rng})(trees)
        self.state = denoise.DenoiseState(
            trees["params"], trees["opt"], jnp.asarray(step, jnp.int32), trees["rng"]
        )
        if batches is not None and hasattr(batches, "load_state_dict"):
            template = {"data": batches.state_dict()}
            try:
                try:  # sharded artifacts store the cursor per-process
                    _, data_trees = ckpt_lib.restore(
                        directory, template, step=step, per_process=("data",)
                    )
                except KeyError:
                    _, data_trees = ckpt_lib.restore(directory, template, step=step)
                batches.load_state_dict(
                    {k: int(v) for k, v in data_trees["data"].items()}
                )
            except KeyError:
                import warnings

                warnings.warn(
                    f"checkpoint step {step} carries no data-iterator state; "
                    "the stream restarts from its initial cursor",
                    stacklevel=2,
                )
        return step

    # -- loop -------------------------------------------------------------
    def fit(self, batches: Iterator[np.ndarray], steps: Optional[int] = None) -> dict:
        """Run the step loop to ``steps`` total steps.  With a checkpoint dir
        the loop auto-resumes from the latest step — so a ``steps`` at or
        below the checkpointed step is a no-op by design.  Drains the async
        checkpoint writer on every exit path, including exceptions — an
        in-flight write must never be stranded by a failing data iterator.

        Crash forensics: with ``forensics_dir`` set, an unhandled exception
        dumps a ``crash-<step>`` bundle (flight-recorder ring, env
        fingerprint, HLO/cost snapshot) before re-raising, and
        ``faulthandler`` is armed to ``<forensics_dir>/faulthandler.log``
        for the crashes Python never sees (segfaults, SIGABRT)."""
        armed = self._forensics is not None and self._forensics.arm_faulthandler()
        try:
            return self._fit(batches, steps)
        except Exception as e:
            self._crash_capture(e)
            raise
        finally:
            if armed:
                self._forensics.disarm_faulthandler()
            try:
                self.finish_saves()
            except Exception:
                # on the normal path _fit already drained (and would have
                # raised); here an original exception from _fit is the one to
                # surface — but the user must still learn the last checkpoint
                # write failed (e.g. ENOSPC), so warn before suppressing
                import traceback
                import warnings

                warnings.warn(
                    "async checkpoint write failed while handling another "
                    "error; the latest checkpoint may be missing:\n"
                    + traceback.format_exc(),
                    stacklevel=2,
                )
            finally:
                # deterministic file lifecycle: exporters' handles close on
                # every exit path (a later log() reopens in append mode)
                try:
                    self.logger.close()
                except OSError:
                    pass  # a full disk must not mask the original error

    def _fit(self, batches: Iterator[np.ndarray], steps: Optional[int] = None) -> dict:
        cfg = self.train_cfg
        steps = steps if steps is not None else cfg.steps
        if cfg.lr_schedule == "cosine" and steps > cfg.steps:
            import warnings

            warnings.warn(
                f"fit(steps={steps}) exceeds TrainConfig.steps={cfg.steps}, "
                "which set the cosine decay horizon — steps past it run at "
                "lr=0; set TrainConfig.steps to the full run length",
                stacklevel=2,
            )
        stateful_stream = hasattr(batches, "state_dict")
        # strict: a garbled manifest must abort the resume, not silently
        # restart from step 0 (the lenient form is for the serving watcher).
        # The resume ANCHOR, though, is the newest step that verifies —
        # not the manifest's raw latest_step, which may name a torn write.
        if cfg.checkpoint_dir and ckpt_lib.latest_step(
            cfg.checkpoint_dir, strict=True
        ) is not None:
            resume_step = integrity.latest_valid_step(
                cfg.checkpoint_dir, observer=self._integrity_obs
            )
            if resume_step is not None:
                resumed = self.restore(
                    cfg.checkpoint_dir, batches=batches, step=resume_step
                )
                self._log(resumed, event=EVENT_RESUME)
            else:
                import warnings

                warnings.warn(
                    f"every checkpoint in {cfg.checkpoint_dir} failed "
                    f"integrity verification and was quarantined — "
                    f"training restarts from step 0",
                    stacklevel=2,
                )

        # Preemption safety (TPU pods get SIGTERM'd): convert the signal to
        # a flag, finish the in-flight step, checkpoint, and return cleanly —
        # a preempted run resumes from its own final state, not the last
        # periodic save.  Handlers only install on the main thread (signal
        # module requirement) and are always restored; a previous handler of
        # None (installed from C, not Python) restores to SIG_DFL.
        self._stop_requested = False
        prev_handlers = {}
        import signal as _signal
        import threading as _threading

        if _threading.current_thread() is _threading.main_thread():
            def _request_stop(signum, frame):
                self._stop_requested = True

            prev_handlers[_signal.SIGTERM] = _signal.signal(
                _signal.SIGTERM, _request_stop
            )

        try:
            return self._fit_loop(batches, steps, cfg, stateful_stream)
        finally:
            for sig, h in prev_handlers.items():
                _signal.signal(sig, h if h is not None else _signal.SIG_DFL)

    def _should_stop(self, poll: bool = True) -> bool:
        """Cross-host agreement on the preemption flag: SIGTERM delivery can
        skew across processes, and per-process checkpoint tiles written at
        different steps would corrupt the resume — so in multi-process runs
        the flag is OR-reduced over hosts and all processes stop at the same
        step.  The allgather is a host-blocking barrier that would defeat
        async-dispatch pipelining if issued every step, so multi-process
        runs only poll it when ``poll`` is True (the caller passes the
        logging cadence — preemption grace windows are tens of seconds, a
        few-step delay is safe).  ``poll`` must be computed identically on
        every host (it gates a collective)."""
        if jax.process_count() == 1:
            return self._stop_requested
        if not poll:
            # NOT the local flag: returning it here would let hosts diverge
            # on the stop step; the decision is deferred to the next poll.
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(self._stop_requested)
        )
        return bool(np.asarray(flags).any())

    # per-step numerics keys: logged as WINDOW aggregates (NumericsMonitor),
    # never as the last step's raw values
    _NUMERICS_KEYS = ("nonfinite_grads", "loss_nonfinite")

    def _drain_steps(self, timer) -> None:
        """Wait out the dispatched step backlog, charging the wait to the
        ``step`` phase.  Called before every BLOCKING phase (eval /
        diagnostics / checkpoint): under async dispatch those phases'
        first device_get would otherwise absorb the queued train compute
        into their own bucket — and since _log_window subtracts them from
        train time, imgs_per_sec would inflate by the backlog fraction."""
        with timer.phase("step"):
            jax.block_until_ready(self.state.params)

    def _numerics_summary(self, step, fetched) -> dict:
        """Fold one window of fetched per-step metrics into the numerics
        monitor; emits the ``nan`` event (and bumps the counter) when the
        window saw nonfinite values.  Shared by the log boundary and the
        logging-disabled surveillance path."""
        num = self._num_mon.update(fetched)
        if num.get("nonfinite_grads") or num.get("loss_nonfinite_steps"):
            self.registry.counter(
                "nan_windows", help="logging windows with nonfinite grads/loss"
            ).inc()
            self._log(
                step, event=EVENT_NAN,
                nonfinite_grads=num["nonfinite_grads"],
                loss_nonfinite_steps=num["loss_nonfinite_steps"],
            )
            # a NaN storm is ONE incident: the trigger engine's debounce
            # collapses the per-window firings into a single bundle
            self._maybe_capture(TRIGGER_NAN, step, {
                "nonfinite_grads": num["nonfinite_grads"],
                "loss_nonfinite_steps": num["loss_nonfinite_steps"],
            })
            if self.train_cfg.halt_on_nan:
                # fail fast BEFORE this iteration's checkpoint phase: the
                # poisoned params must never enter the resume lineage.
                # Detection is window-granular, so keep log_every at or
                # below checkpoint_every for an airtight guarantee.
                raise NonFiniteError(
                    f"nonfinite grads/loss detected at step {step} "
                    f"(nonfinite_grads={num['nonfinite_grads']}, "
                    f"loss_nonfinite_steps={num['loss_nonfinite_steps']}); "
                    f"halting so a supervisor can resume from the last "
                    f"clean checkpoint"
                )
        return num

    def _log_window(self, step, timer, window_metrics, window_imgs, cfg):
        """Cut one logging window: fetch the window's per-step device
        scalars (the loop's ONLY host sync), fold in the health monitors,
        and emit the phase-timed record.  Returns the logged step's plain
        metrics (fit()'s return value contract)."""
        with timer.phase("log_sync"):
            fetched = jax.device_get(window_metrics)
        last = {
            k: float(v) for k, v in fetched[-1].items()
            if k not in self._NUMERICS_KEYS
        }
        num = self._numerics_summary(step, fetched) if cfg.monitor_numerics else {}
        mem = self._mem_mon.sample()
        phases = timer.window()
        # the throughput of record: images over TRAIN time — eval,
        # checkpoint, diagnostics, and exporter IO no longer silently
        # deflate imgs/sec (they are reported as their own phases instead)
        overhead = sum(
            phases.get(f"t_{p}", 0.0)
            for p in ("eval", "checkpoint", "diag", "log_emit")
        )
        train_dt = max(phases["t_window"] - overhead, 1e-9)
        # everything from the window cut to the end of exporter IO is
        # charged to the next window's log_emit phase
        t_emit = time.monotonic()
        self.registry.counter("steps_total", help="train steps completed").inc(
            phases["window_steps"]
        )
        self.registry.counter("imgs_total", help="images consumed").inc(window_imgs)
        for k in ("loss", "grad_norm"):
            if k in last:
                self.registry.gauge(k).set(last[k])
        for k, v in mem.items():
            self.registry.gauge(k, unit="bytes").set(v)
        self._log(
            step,
            imgs_per_sec=window_imgs / train_dt,
            imgs_per_sec_per_chip=window_imgs / train_dt / jax.device_count(),
            **last, **num, **mem, **phases,
        )
        if num.get("grad_norm_spike"):
            self._maybe_capture(TRIGGER_GRAD_SPIKE, step, {
                "grad_norm": last.get("grad_norm"),
            })
        if self._steptime_mon is not None and phases["window_steps"]:
            regression = self._steptime_mon.update(
                train_dt / phases["window_steps"]
            )
            if regression is not None:
                self._maybe_capture(TRIGGER_STEP_TIME, step, regression)
        # exporter IO is attributed to the NEXT window's log_emit phase
        # (the record that pays it is the one being written)
        timer.add("log_emit", time.monotonic() - t_emit)
        return last

    def _fit_loop(self, batches, steps, cfg, stateful_stream):
        last_metrics = {}
        last_saved = -1
        window_imgs = 0
        window_metrics = []   # per-step device-scalar dicts; fetched ONCE
                              # at the log boundary (no per-step host sync)
        timer = PhaseTimer(registry=self.registry, tracer=self.tracer)
        emitted_recompiles = self._recompile_mon.recompiles
        start_step = int(jax.device_get(self.state.step))  # glomlint: disable=jax-host-sync -- one fetch before the loop body, not per-step
        profiling = False
        completed = steps
        stopped = False
        # multi-process stop-flag poll cadence (see _should_stop): piggyback
        # on the logging/checkpoint cadence when one is set, but never wait
        # more than cfg.stop_poll_steps — preemption grace windows are tens
        # of seconds and a large checkpoint_every must not starve the flag.
        # The cap is a step count, not wall-clock (the poll gates a
        # collective, so it must be computed identically on every host);
        # runs with multi-second steps should lower cfg.stop_poll_steps so
        # stop_poll * step_time stays inside the grace window.  Absolute
        # step numbers so the poll lands on the same steps as the logging
        # barrier after a resume.
        stop_poll = min(
            x
            for x in (cfg.log_every, cfg.checkpoint_every, cfg.stop_poll_steps)
            if x
        )
        for i in range(start_step, steps):
            if cfg.profile_dir:
                # trace a 3-step post-warmup window (steps 2,3,4 of this run),
                # draining pending async work at both edges so earlier steps
                # don't bleed into the capture
                if i == start_step + 2 and not profiling:
                    jax.block_until_ready(self.state.params)  # glomlint: disable=jax-host-sync -- profiler-window edge: the trace must not start mid-dispatch; fires on exactly one step
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                elif profiling and i == start_step + 5:
                    jax.block_until_ready(self.state.params)  # glomlint: disable=jax-host-sync -- profiler-window edge: drain so the trace holds whole steps; fires on exactly one step
                    jax.profiler.stop_trace()
                    profiling = False
            with timer.phase("data_wait"):
                img = next(batches)
            with timer.phase("h2d"):
                img = jax.device_put(img, self._batch_sh)
            if self._forensics is not None:
                # abstract spec only (a tiny host object, no sync): the
                # HLO snapshot lowers against the shapes the step last saw
                self._last_batch_spec = jax.ShapeDtypeStruct(img.shape, img.dtype)
            if cfg.eval_every and (i + 1) % cfg.eval_every == 0:
                self._drain_steps(timer)
                with timer.phase("eval"):
                    if self._eval_suite is not None:
                        # held-out evaluation: PSNR + linear probe on data
                        # the step function NEVER consumes
                        ev = self._eval_suite.run(
                            self.state.params, jax.random.PRNGKey(cfg.seed + i)
                        )
                        self._log(i + 1, **ev)
                    elif self._eval is not None:
                        # legacy fallback (no suite given): evaluate BEFORE
                        # the step consumes this batch, so the PSNR reflects
                        # params that have not trained on these images
                        psnr = self._eval(
                            self.state.params, img, jax.random.PRNGKey(cfg.seed + i)
                        )
                        self._log(i + 1, psnr_db=float(jax.device_get(psnr)))  # glomlint: disable=jax-host-sync -- eval-cadence fetch inside the timed eval phase, not the step path
            with timer.phase("step"):
                # dispatch only — under async dispatch the device compute
                # this enqueues is paid for in `log_sync` at the boundary
                self.state, metrics = self._step(self.state, img)
            timer.count_step()
            window_imgs += img.shape[0]
            if self._forensics is not None and self._forensics.trace_due(i + 1):
                # end the triggered trace window: drain the dispatched
                # backlog first (charged to `step`, like every blocking
                # phase) so the trace holds the steps it promises
                self._drain_steps(timer)
                self._forensics.stop_trace()
            if cfg.log_every or cfg.monitor_numerics:
                window_metrics.append(metrics)
            if self._recompile_mon.poll() and (
                self._recompile_mon.recompiles > emitted_recompiles
            ):
                # cache growth past the expected first compile: a shape or
                # dtype changed under the jit — surface it the moment it
                # happens, with the step that triggered it
                emitted_recompiles = self._recompile_mon.recompiles
                self.registry.counter(
                    "recompiles", help="XLA recompilations of the train step "
                    "after the first compile"
                ).inc()
                self._log(
                    i + 1, event=EVENT_RECOMPILE,
                    compile_count=self._recompile_mon.compiles,
                )
                self._maybe_capture(TRIGGER_RECOMPILE, i + 1, {
                    "compile_count": self._recompile_mon.compiles,
                    "recompiles": self._recompile_mon.recompiles,
                })
            if self._diag is not None and (i + 1) % cfg.diag_every == 0:
                self._drain_steps(timer)
                with timer.phase("diag"):
                    diag = flatten_diagnostics(
                        self._diag(self.state.params["glom"], img)
                    )
                for k in ("island_agreement", "attn_entropy"):
                    self.registry.gauge(k).set(diag[k])
                self._log(i + 1, **diag)
            if cfg.log_every and (i + 1) % cfg.log_every == 0:
                last_metrics = self._log_window(
                    i + 1, timer, window_metrics, window_imgs, cfg
                )
                window_metrics, window_imgs = [], 0
            elif not cfg.log_every and cfg.monitor_numerics and (
                (i + 1) % stop_poll == 0
            ):
                # logging disabled: NaN surveillance still runs, at the
                # stop-poll cadence — bounded accumulation, and only the
                # nan event record is ever emitted
                fetched = jax.device_get(window_metrics)  # glomlint: disable=jax-host-sync -- the ONE stop-poll-cadence fetch the windowed accumulation exists to bound
                window_metrics = []
                self._numerics_summary(i + 1, fetched)
            if (
                cfg.checkpoint_every
                and cfg.checkpoint_dir
                and (i + 1) % cfg.checkpoint_every == 0
            ):
                self._drain_steps(timer)
                with timer.phase("checkpoint"):
                    self.save(
                        cfg.checkpoint_dir,
                        data_state=batches.state_dict() if stateful_stream else None,
                    )
                last_saved = i + 1
            with timer.phase("stop_poll"):
                stop = self._should_stop((i + 1) % stop_poll == 0)
            if stop:
                self._log(i + 1, event=EVENT_PREEMPT_STOP)
                if self._forensics is not None:
                    # terminal path, engine bypassed (fires once).  NO HLO
                    # snapshot and no trace: a possible recompile inside
                    # the preemption grace window could cost the final
                    # checkpoint this stop exists to write.
                    self._forensics.stop_trace()
                    self._forensics.capture(
                        TRIGGER_PREEMPT, i + 1, {"reason": "SIGTERM"},
                        snapshot=False, trace=False,
                    )
                completed = i + 1
                stopped = True
                break
        jax.block_until_ready(self.state.params)  # glomlint: disable=jax-host-sync -- loop-exit drain: fit() must not return with dispatched work in flight
        if profiling:
            jax.profiler.stop_trace()
        if self._forensics is not None:
            self._forensics.stop_trace()  # a trace window outliving the loop
        if window_metrics and cfg.monitor_numerics:
            # tail steps past the last boundary (including the ones right
            # before a preemption stop — where a diverging run most likely
            # went nonfinite) still get NaN surveillance; the partial
            # window's throughput record stays dropped as before
            self._numerics_summary(completed, jax.device_get(window_metrics))  # glomlint: disable=jax-host-sync -- post-loop tail fetch; the step loop has already exited
        # Final/preemption save: periodic saves need checkpoint_every, but a
        # preemption save must happen whenever a checkpoint_dir exists at
        # all — otherwise a checkpoint_every=0 run that catches SIGTERM
        # would exit cleanly WITHOUT the state the stop marker promises.
        if (cfg.checkpoint_dir and (cfg.checkpoint_every or stopped)
                and last_saved != completed and start_step < completed):
            self.save(
                cfg.checkpoint_dir,
                data_state=batches.state_dict() if stateful_stream else None,
            )
        self.finish_saves()  # fit returns only once the checkpoint is durable
        timer.close()  # the tail window's root span must close before export
        if cfg.trace_dir and jax.process_index() == 0:
            # Perfetto-loadable export of the run's phase spans (best
            # effort — an unwritable dir must not fail a finished fit)
            import os

            from glom_tpu.obs import TraceExporter

            try:
                os.makedirs(cfg.trace_dir, exist_ok=True)
                TraceExporter(self.tracer.sink).write(
                    os.path.join(cfg.trace_dir, "train_trace.json"))
            except OSError as e:
                import warnings

                warnings.warn(f"trace export failed ({e})", stacklevel=2)
        return last_metrics
