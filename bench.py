"""Benchmark of record.

Measures sustained denoising-SSL training throughput (imgs/sec/chip) for the
flagship reference config — Glom(dim=512, levels=6, image=224, patch=14),
iters=12, the BASELINE.json metric of record — on the attached device, and
prints ONE JSON line.

``vs_baseline`` compares against the BASELINE.json north-star rate of
>2,000 imgs/sec aggregate on a v4-32 slice.  v4-32 = 32 TensorCores =
16 chips (one JAX device per megacore chip), so the per-chip target is
2000/16 = 125 imgs/sec/chip (the reference itself publishes no numbers —
BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import threading
import time

NORTH_STAR_IMGS_PER_SEC_PER_CHIP = 2000.0 / 16.0

# Latest flagship rate this code achieved on real hardware — update together
# with the BASELINE.md round table whenever a window lands a new record.
# Quoted by the dead-tunnel error line (only for a default-flags invocation,
# i.e. the configuration the number was actually measured under).
LAST_MEASURED_FLAGSHIP = {
    "value": 288.6,
    "when": "2026-07-31 round-5 window, TPU v5e (1 chip)",
    "config": "ff_impl=pallas (bf16, remat=dots, batch 32)",
    "provenance": "BASELINE.md round-5 table",
}  # vs_baseline is derived at emit time from NORTH_STAR_IMGS_PER_SEC_PER_CHIP


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="flagship", choices=["flagship", "large", "tiny"],
                   help="flagship = BASELINE config 1-3 (512/6/224/14, iters 12); "
                        "large = BASELINE config 4 (1024/8/384/16, iters 16); "
                        "tiny = 64/3/64/8 smoke config (CPU-runnable plumbing "
                        "check, never a number of record)")
    p.add_argument("--batch-size", type=int, default=0, help="0 = auto by device kind")
    p.add_argument("--steps", type=int, default=0, help="0 = auto (20 on TPU, 2 on CPU)")
    p.add_argument("--warmup", type=int, default=-1, help="-1 = auto (3 on TPU, 1 on CPU)")
    p.add_argument("--fp32", action="store_true", help="disable bf16 compute")
    p.add_argument("--no-remat", action="store_true",
                   help="disable scan-body rematerialization (needs small batch)")
    p.add_argument("--remat-policy", default="dots", choices=["full", "dots"])
    p.add_argument("--fuse-ff", action="store_true",
                   help="run bottom_up+top_down as one 2L-1-group call")
    p.add_argument("--scan-unroll", type=int, default=1,
                   help="unroll factor of the iteration scan (>1 lets XLA "
                        "fuse/overlap across iterations; loop is 7-16 steps)")
    p.add_argument("--attention-impl", default="dense", choices=["auto", "dense", "pallas", "ring", "ulysses"])
    p.add_argument("--ff-impl", default="auto",
                   choices=["auto", "dense", "pallas", "fused"],
                   help="auto = pallas on TPU (the fastest hardware-verified "
                        "config: ~+10%% over dense, 282.4 vs 255.6 in the "
                        "round-2 window), dense on the CPU fallback "
                        "(interpret-mode pallas would be pathologically "
                        "slow); fused = the single-launch level-update "
                        "kernel (consensus + both FFs in one Pallas call — "
                        "the candidate to dethrone pallas, falls back to it "
                        "where its shape predicates fail)")
    p.add_argument("--fused-ff-bwd", action="store_true",
                   help="with --ff-impl pallas: fused Pallas backward kernels "
                        "instead of the default XLA einsum VJP")
    p.add_argument("--data", default="synthetic", choices=["synthetic", "images"],
                   help="synthetic = one resident host batch reused every "
                        "step (pure device rate, the metric of record); "
                        "images = stream real JPEG batches from --data-dir "
                        "through ImageFolderStream each step (end-to-end "
                        "input-path rate: decode threads + H2D overlap)")
    p.add_argument("--data-dir", default=None,
                   help="ImageFolder root for --data images (e.g. generated "
                        "by examples/make_shapes_dataset.py)")
    p.add_argument("--data-workers", type=int, default=8,
                   help="decode threads for --data images")
    p.add_argument("--decode", default="auto", choices=["auto", "python"],
                   help="--data images decode path: auto = native C++ "
                        "libjpeg batch decoder when available, python = "
                        "force the per-file cv2/PIL thread pool (A/B lever)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of a 3-step window "
                        "(after warmup, excluded from the timed window) — "
                        "the MFU/trace evidence leg; failures to trace are "
                        "non-fatal so the number of record still prints")
    p.add_argument("--device-probe-timeout", type=int, default=240,
                   help="seconds to retry-poll the accelerator relay before "
                        "emitting an error JSON line and exiting; <= 0 "
                        "disables; ignored when --platform forces a local "
                        "backend")
    p.add_argument("--platform", default="auto", choices=["auto", "cpu"],
                   help="cpu = force the local CPU backend via jax.config "
                        "(relay guard skipped — nothing can hang), making "
                        "`bench.py --config tiny --platform cpu` a "
                        "tunnel-free plumbing check of the full bench path")
    args = p.parse_args()

    metric = "denoise_ssl_train_imgs_per_sec_per_chip"
    if args.config != "flagship":
        metric += f"_{args.config}"
    if args.data == "images":
        metric += "_realdata"
        if not args.data_dir:
            raise SystemExit("--data images needs --data-dir")

    def _emit_error(msg):
        # An unreachable accelerator is an OUTAGE, not a measurement: emit a
        # distinct "skipped" status (no zero value) so the bench gate
        # (tools/bench_gate.py) and trend tooling never read a dead tunnel
        # as a 100% throughput regression.  Genuine measurement faults keep
        # the structured-error shape (value 0.0 + "error").
        skipped = "unreachable" in msg or "device init exceeded" in msg
        if skipped:
            rec = {
                "metric": metric,
                "unit": "imgs/sec/chip",
                "status": "skipped",
                "reason": msg,
            }
        else:
            rec = {
                "metric": metric,
                "value": 0.0,
                "unit": "imgs/sec/chip",
                "vs_baseline": 0.0,
                "error": msg,
            }
        # a dead tunnel zeroes the capture, but the latest number this code
        # achieved on hardware is on record — carry it (with provenance) so
        # the error line still points at measured data.  Only for the
        # default-flags invocation (the driver's `python bench.py`): a sweep
        # leg with perf flags describes a different configuration than the
        # record and must not have the pallas number attributed to it.
        # Compared against the parser's own defaults so a future default
        # change or new perf flag cannot silently desynchronize the gate;
        # only flags that don't alter the measured configuration are exempt.
        non_perf = {"device_probe_timeout", "steps", "warmup", "profile_dir",
                    "data_workers", "data_dir", "decode"}
        default_flags = (
            # ff_impl "auto" resolves to pallas on TPU = the record's config;
            # batch 32 is what the auto batch resolves to for flagship-on-TPU
            args.ff_impl in ("auto", "pallas") and args.batch_size in (0, 32)
            and all(getattr(args, k) == p.get_default(k)
                    for k in vars(args) if k not in non_perf | {"ff_impl", "batch_size"})
        )
        if default_flags:
            rec["last_measured"] = dict(
                LAST_MEASURED_FLAGSHIP,
                vs_baseline=round(LAST_MEASURED_FLAGSHIP["value"]
                                  / NORTH_STAR_IMGS_PER_SEC_PER_CHIP, 2),
            )
        print(json.dumps(rec), flush=True)
        if (skipped and "unreachable" in msg
                and threading.current_thread() is threading.main_thread()):
            # the relay retry-poll path calls emit on the MAIN thread then
            # raises SystemExit(2); exiting 0 here makes the skip non-fatal
            # (a result was never obtainable).  The init-watchdog calls emit
            # from its timer thread, where a raise would be swallowed by
            # threading and cancel its os._exit(2) — there the record is
            # emitted and the watchdog hard-exits 2; consumers must key on
            # the status field, not the return code.
            raise SystemExit(0)

    # Device guard (shared with tools/breakdown.py): retry-poll the relay,
    # then watchdog the single init attempt — a dead or wedged tunnel must
    # produce a JSON error line, never a silent hang.
    from glom_tpu.device_guard import guarded_jax_init

    jax, timer = guarded_jax_init(args.platform, args.device_probe_timeout,
                                  _emit_error)

    try:
        # persistent compile cache: a bench run after a prior sweep (or a
        # driver run after the builder's) skips the 20-40s first compile
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax: cache flags absent; compile cold
    import jax.numpy as jnp

    from glom_tpu.config import GlomConfig, TrainConfig, bench_preset
    from glom_tpu.parallel.mesh import is_tpu_device
    from glom_tpu.training.data import synthetic_batches
    from glom_tpu.training.trainer import Trainer

    on_tpu = jax.devices()[0].platform != "cpu"
    if timer is not None:
        timer.cancel()  # device init completed; the guarded window is over
    if args.ff_impl == "auto":
        # pltpu kernels only lower on TPU; any other backend (cpu, gpu) takes
        # the dense XLA path
        from glom_tpu.parallel.mesh import default_backend_is_tpu

        args.ff_impl = "pallas" if default_backend_is_tpu() else "dense"
    # CPU fallback exists so the bench cannot wedge a driver run; the metric
    # stays honest (it just reports the low CPU rate)
    if args.steps == 0:
        args.steps = 20 if on_tpu else 2
    if args.warmup < 0:
        args.warmup = 3 if on_tpu else 1
    model_kwargs, iters, tpu_b, cpu_b = bench_preset(args.config)
    per_chip_batch = tpu_b if on_tpu else cpu_b
    batch = args.batch_size or per_chip_batch * jax.device_count()

    config = GlomConfig(
        compute_dtype=jnp.float32 if args.fp32 else jnp.bfloat16,
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
        fuse_ff=args.fuse_ff,
        scan_unroll=args.scan_unroll,
        attention_impl=args.attention_impl,
        ff_impl=args.ff_impl,
        ff_fused_bwd=args.fused_ff_bwd,
        **model_kwargs,
    )
    train = TrainConfig(batch_size=batch, iters=iters, log_every=0)
    trainer = Trainer(config, train)

    if args.data == "images":
        # full input path: disk JPEGs -> decode threads -> H2D, fresh batch
        # every step (the stream's internal prefetch overlaps decode with
        # the previous step's device compute)
        from glom_tpu.training.image_stream import ImageFolderStream

        batches = ImageFolderStream(
            args.data_dir, batch, config.image_size,
            process_index=0, process_count=1, workers=args.data_workers,
            native_decode=None if args.decode == "auto" else False,
        )

        def next_img():
            return jax.device_put(next(batches), trainer._batch_sh)
    else:
        batches = synthetic_batches(batch, config.image_size)
        resident = jax.device_put(next(batches), trainer._batch_sh)

        def next_img():
            return resident

    state = trainer.state
    for _ in range(args.warmup):
        state, metrics = trainer._step(state, next_img())
    jax.block_until_ready(state.params)

    # recompile guard (glom_tpu.obs): warmup compiled the step once; any
    # cache growth during the timed window means the window paid a silent
    # XLA recompile and the rate is not a steady-state measurement
    from glom_tpu.obs import RecompileMonitor

    recompile_mon = RecompileMonitor(trainer._step)
    recompile_mon.poll()  # absorb the warmup compile(s)

    if args.profile_dir:
        # same trace plumbing and phase names as the Trainer's loop
        # (glom_tpu.profiling.trace + annotate), so a bench trace and a
        # trainer trace read identically in TensorBoard/Perfetto
        from glom_tpu.profiling import annotate, trace as profiler_trace

        try:
            with profiler_trace(args.profile_dir):
                for _ in range(3):
                    if args.data == "images":
                        # split exactly like the trainer's phases: decode
                        # stall is data_wait, the transfer is h2d
                        with annotate("data_wait"):
                            host = next(batches)
                        with annotate("h2d"):
                            img = jax.device_put(host, trainer._batch_sh)
                    else:
                        with annotate("data_wait"):
                            img = next_img()  # resident batch, no H2D
                    with annotate("step"):
                        state, metrics = trainer._step(state, img)
                jax.block_until_ready(state.params)
            print(f"# trace written to {args.profile_dir}", flush=True)
        except Exception as e:  # tracing must never cost the number of record
            print(f"# trace failed ({type(e).__name__}: {e})", flush=True)

    def timed_window():
        # monotonic, not wall clock: an NTP step during the window corrupts
        # time.time() deltas (observed 2026-07-31: batch-128 leg printed an
        # impossible 510k imgs/sec between two sane legs)
        t0 = time.monotonic()
        nonlocal_state = state
        for _ in range(args.steps):
            nonlocal_state, _m = trainer._step(nonlocal_state, next_img())
        jax.block_until_ready(nonlocal_state.params)
        return time.monotonic() - t0, nonlocal_state

    dt, state = timed_window()

    imgs_per_sec = batch * args.steps / dt
    per_chip = imgs_per_sec / jax.device_count()

    # The BASELINE.json north star is defined for the flagship config only;
    # other configs score against a FLOP-scaled equivalent target
    # (per-image cost ∝ dim^2 * (L + L-1) * n * iters for the dominant FFs).
    def rel_cost(c, it):
        return (c.dim ** 2) * (2 * c.levels - 1) * c.num_patches * it

    flagship_cost = rel_cost(GlomConfig(), 12)
    target = NORTH_STAR_IMGS_PER_SEC_PER_CHIP * flagship_cost / rel_cost(config, iters)
    if per_chip > 20 * target:
        # physically implausible (>20x the FLOP-scaled north star): a timing
        # fault, not a measurement — re-measure once before giving up
        dt, state = timed_window()
        imgs_per_sec = batch * args.steps / dt
        per_chip = imgs_per_sec / jax.device_count()
    result = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / target, 3),
        "status": "ok",
        # the CPU fallback keeps the metric honest but is NOT the hardware
        # trajectory: the bench gate skips (outage, not regression) when a
        # measured record says backend != tpu.  is_tpu_device, not platform:
        # the relay's PJRT plugin registers platform 'axon' with a TPU
        # device_kind, and a GPU must stamp 'gpu' so the gate skips it too
        "backend": ("tpu" if is_tpu_device(jax.devices()[0])
                    else jax.devices()[0].platform),
    }
    window_recompiles = recompile_mon.poll()
    if window_recompiles:
        # annotate, don't zero: the number is real wall-clock, it just
        # includes compile time — the reader must know why it is low
        result["recompiles_in_window"] = window_recompiles
    if per_chip > 20 * target:
        result.update(value=0.0, vs_baseline=0.0, status="error",
                      error=f"implausible rate {per_chip:.0f} imgs/s/chip after "
                            "re-measure (>20x scaled target) — timing fault")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
